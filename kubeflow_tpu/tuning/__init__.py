"""Hyperparameter tuning runtime (the Katib/Vizier analogue).

The reference deploys vizier-core + per-algorithm suggestion services
(kubeflow/katib/suggestion.libsonnet:3-10: random, grid, hyperband,
bayesianoptimization) and a StudyJobController whose metricsCollector CronJob
scrapes worker logs (studyjobcontroller.libsonnet:115-147). Here the same
pieces are in-process: suggestion algorithms as a library, the study
controller spawning trial JaxJobs, metrics flowing through job status.
"""

from kubeflow_tpu.tuning.suggestions import get_algorithm
from kubeflow_tpu.tuning.controller import StudyJobController

__all__ = ["get_algorithm", "StudyJobController"]
