"""Tuning sweep CLI — the CI face of the self-tuning engine.

``python -m kubeflow_tpu.tuning.sweep --scenario synthetic-knobs
--policies random,tpe --trials 12 --seed 7 --promote`` runs one full
Experiment per policy through the REAL ExperimentController on the fake
apiserver (same reconcile loop, same suggestion algorithms, same
scenario registry as the cluster path) and emits one JSON record:

- per-policy best objective and best-so-far trace (monotone by
  construction of the experiment status — the CI gate re-checks it);
- trial economy: the first trial index at which each later policy
  reaches the FIRST policy's final best (the ISSUE gate: bayesian/tpe
  must reach random's best in at most half the trials);
- improvement over the checked-in defaults (trial 0 is always the
  baseline) and, with ``--promote``, the recorded promotion of the
  winner onto a target InferenceService (versions + engine overrides —
  what the rollout controller walks in a real cluster).
"""

from __future__ import annotations

import argparse
import json
import sys


def run_policy(scenario: str, policy: str, trials: int, seed: int,
               promote: bool) -> dict:
    from kubeflow_tpu.apis import jobs as jobs_api
    from kubeflow_tpu.apis.experiment import experiment, experiment_crd
    from kubeflow_tpu.apis.inference import (
        inference_service,
        inference_service_crd,
    )
    from kubeflow_tpu.k8s.fake import FakeApiServer
    from kubeflow_tpu.operators.experiment import ExperimentController

    api = FakeApiServer()
    api.ensure_namespace("kubeflow")
    api.apply(experiment_crd())
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    promotion = None
    if promote:
        api.apply(inference_service_crd())
        svc = inference_service("sweep-target", "kubeflow", "lm-test-tiny")
        for obj in (svc if isinstance(svc, list) else [svc]):
            if obj.get("kind") == "InferenceService":
                api.create(obj)
        promotion = {"target": "sweep-target",
                     "minImprovementPercent": 0.0}
    api.create(experiment(
        f"sweep-{policy}", "kubeflow", scenario,
        algorithm=policy, max_trials=trials, parallel_trials=2,
        seed=seed, promotion=promotion))
    ctrl = ExperimentController(api)
    for _ in range(trials + 4):
        ctrl.reconcile_all()
        got = api.get("kubeflow-tpu.org/v1", "Experiment",
                      f"sweep-{policy}", "kubeflow")
        if got["status"].get("state") in ("Succeeded", "Failed"):
            break
    status = got["status"]
    done = sorted(
        (t for t in status.get("trials", [])
         if t.get("objectiveValue") is not None),
        key=lambda t: t["index"])
    trace, best = [], None
    for t in done:
        v = float(t["objectiveValue"])
        best = v if best is None else max(best, v)
        trace.append(round(best, 6))
    out = {
        "policy": policy,
        "state": status.get("state"),
        "seed": status.get("seed"),
        "trials": len(status.get("trials", [])),
        "bestObjectiveValue": status.get("bestObjectiveValue"),
        "bestAssignments": status.get("bestAssignments"),
        "baselineObjectiveValue": status.get("baselineObjectiveValue"),
        "improvementPercent": status.get("improvementPercent"),
        "bestSoFarTrace": trace,
    }
    if promote:
        out["promotion"] = status.get("promotion")
        svc = api.get("kubeflow-tpu.org/v1", "InferenceService",
                      "sweep-target", "kubeflow")
        out["promotedVersions"] = svc["spec"].get("versions")
    return out


def trials_to_reach(trace: list[float], target: float) -> int | None:
    """1-based trial count at which best-so-far first reaches target."""
    for i, v in enumerate(trace):
        if v >= target:
            return i + 1
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario", default="synthetic-knobs")
    ap.add_argument("--policies", default="random,tpe",
                    help="comma list; the FIRST is the economy baseline")
    ap.add_argument("--trials", type=int, default=12)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--promote", action="store_true",
                    help="promote each policy's winner onto a fake "
                         "InferenceService and record the versions write")
    args = ap.parse_args(argv)

    policies = [p.strip() for p in args.policies.split(",") if p.strip()]
    results = {p: run_policy(args.scenario, p, args.trials, args.seed,
                             args.promote)
               for p in policies}
    record: dict = {
        "scenario": args.scenario,
        "seed": args.seed,
        "maxTrials": args.trials,
        "policies": results,
        "regression": False,
    }
    reasons = []
    for p, r in results.items():
        if r["state"] != "Succeeded":
            reasons.append(f"{p} experiment ended {r['state']}")
        trace = r["bestSoFarTrace"]
        if any(b < a for a, b in zip(trace, trace[1:])):
            reasons.append(f"{p} best-so-far trace not monotone")
        if (r.get("improvementPercent") is None
                or r["improvementPercent"] <= 0):
            reasons.append(
                f"{p} found nothing better than the defaults "
                f"(improvement {r.get('improvementPercent')}%)")
        if args.promote and not (r.get("promotion") or {}).get("version"):
            reasons.append(f"{p} promotion not recorded")
    if len(policies) > 1:
        base = policies[0]
        base_best = results[base].get("bestObjectiveValue")
        base_n = len(results[base]["bestSoFarTrace"])
        for p in policies[1:]:
            n = trials_to_reach(results[p]["bestSoFarTrace"],
                                float(base_best))
            record[f"{p}TrialsToReach_{base}Best"] = n
            if n is None or n > base_n / 2:
                reasons.append(
                    f"{p} needed {n} trials to reach {base}'s best "
                    f"({base_best}); gate is <= {base_n // 2}")
    if reasons:
        record["regression"] = True
        record["reasons"] = reasons
    print(json.dumps(record, indent=2, default=str))
    return 1 if record["regression"] else 0


if __name__ == "__main__":
    sys.exit(main())
