"""StudyJob controller.

The studyjobcontroller analogue (kubeflow/katib/studyjobcontroller.libsonnet):
reconcile a StudyJob by spawning trial jobs from the trial template with
``${trialParameters.<name>}`` substituted, reading each finished trial's
objective from its job status (the metricsCollector path — trials publish
final metrics into ``.status.metrics``, see kubeflow_tpu/train/loop.py), and
asking the suggestion algorithm for the next assignments.
"""

from __future__ import annotations

import copy
import re

from kubeflow_tpu.apis.jobs import JOBS_API_VERSION
from kubeflow_tpu.apis.tuning import STUDY_JOB_KIND, TUNING_API_VERSION
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.operators.base import Controller
from kubeflow_tpu.tuning.suggestions import (
    Observation,
    domains_from_spec,
    get_algorithm,
)

LABEL_STUDY = "kubeflow-tpu.org/study-name"
LABEL_TRIAL = "kubeflow-tpu.org/trial-index"

_PARAM_RE = re.compile(r"\$\{trialParameters\.([A-Za-z0-9_]+)\}")


def substitute_parameters(template, assignments: dict):
    """Replace ${trialParameters.x} through the whole object tree; a string
    that is exactly one placeholder takes the raw typed value."""

    def sub(node):
        if isinstance(node, dict):
            return {key: sub(value) for key, value in node.items()}
        if isinstance(node, list):
            return [sub(item) for item in node]
        if isinstance(node, str):
            m = _PARAM_RE.fullmatch(node)
            if m:
                return assignments[m.group(1)]
            return _PARAM_RE.sub(
                lambda m: str(assignments[m.group(1)]), node
            )
        return node

    return sub(copy.deepcopy(template))


class StudyJobController(Controller):
    api_version = TUNING_API_VERSION
    kind = STUDY_JOB_KIND
    resync_seconds = 10.0

    def watched_kinds(self):
        return [(JOBS_API_VERSION, "JaxJob")]

    def reconcile(self, study: dict) -> None:
        study = copy.deepcopy(study)
        spec = study["spec"]
        status = study.setdefault("status", {})
        if status.get("state") in ("Succeeded", "Failed"):
            return
        status.setdefault("state", "Running")
        trials = status.setdefault("trials", [])

        self._collect_finished(study, trials)

        objective = spec.get("objective", {})
        maximize = objective.get("type", "maximize") == "maximize"
        finished = [t for t in trials if t["state"] in ("Succeeded", "Failed")]
        succeeded = [t for t in finished if t["state"] == "Succeeded"
                     and t.get("objectiveValue") is not None]
        failed = [t for t in finished if t["state"] == "Failed"]

        self._update_best(status, succeeded, maximize)

        goal = objective.get("goal")
        best = status.get("bestObjectiveValue")
        goal_met = (
            goal is not None and best is not None
            and (best >= goal if maximize else best <= goal)
        )
        if len(failed) > spec.get("maxFailedTrialCount", 3):
            status["state"] = "Failed"
        elif goal_met or len(finished) >= spec.get("maxTrialCount", 10):
            status["state"] = "Succeeded"
        else:
            self._spawn_trials(study, trials, maximize)

        status["completedTrialCount"] = len(finished)
        self._push_status(study)

    # ------------------------------------------------------------------

    def _trial_job_name(self, study: dict, index: int) -> str:
        return f"{study['metadata']['name']}-trial-{index}"

    def _collect_finished(self, study: dict, trials: list[dict]) -> None:
        ns = study["metadata"]["namespace"]
        metric = study["spec"].get("objective", {}).get(
            "objectiveMetricName", "loss"
        )
        for trial in trials:
            if trial["state"] in ("Succeeded", "Failed"):
                continue
            job = self.client.get_or_none(
                JOBS_API_VERSION, "JaxJob",
                self._trial_job_name(study, trial["index"]), ns,
            )
            if job is None:
                continue
            jstate = job.get("status", {}).get("state")
            if jstate == "Succeeded":
                trial["state"] = "Succeeded"
                metrics = job.get("status", {}).get("metrics", {})
                if metric in metrics:
                    trial["objectiveValue"] = float(metrics[metric])
            elif jstate == "Failed":
                trial["state"] = "Failed"

    def _update_best(self, status: dict, succeeded: list[dict],
                     maximize: bool) -> None:
        if not succeeded:
            return
        best = (max if maximize else min)(
            succeeded, key=lambda t: t["objectiveValue"]
        )
        status["bestObjectiveValue"] = best["objectiveValue"]
        status["bestTrialIndex"] = best["index"]
        status["bestAssignments"] = best["assignments"]

    def _spawn_trials(self, study: dict, trials: list[dict],
                      maximize: bool) -> None:
        spec = study["spec"]
        ns = study["metadata"]["namespace"]
        active = [t for t in trials
                  if t["state"] not in ("Succeeded", "Failed")]
        budget = min(
            spec.get("parallelTrialCount", 2) - len(active),
            spec.get("maxTrialCount", 10) - len(trials),
        )
        if budget <= 0:
            return

        domains = domains_from_spec(spec.get("parameters", []))
        algo = get_algorithm(
            spec.get("algorithm", "random"), domains,
            seed=len(trials),
        )
        observations = [
            Observation(
                t["assignments"],
                t["objectiveValue"] if maximize else -t["objectiveValue"],
            )
            for t in trials
            if t["state"] == "Succeeded" and t.get("objectiveValue") is not None
        ]
        for _ in range(budget):
            assignments = algo.next(observations)
            if assignments is None:  # space exhausted (grid)
                if not active:
                    study["status"]["state"] = "Succeeded"
                return
            index = len(trials)
            job = substitute_parameters(spec["trialTemplate"], assignments)
            job.setdefault("apiVersion", JOBS_API_VERSION)
            job.setdefault("kind", "JaxJob")
            meta = job.setdefault("metadata", {})
            meta["name"] = self._trial_job_name(study, index)
            meta["namespace"] = ns
            meta.setdefault("labels", {}).update({
                LABEL_STUDY: study["metadata"]["name"],
                LABEL_TRIAL: str(index),
            })
            meta["ownerReferences"] = [k8s.object_ref(study)]
            self.client.create(job)
            trials.append({
                "index": index,
                "assignments": assignments,
                "state": "Running",
                "jobName": meta["name"],
            })

    # Status writes go through Controller._push_status: the trial-spawn
    # reconcile races pod events requeuing the study, so conflicts are
    # refetched-and-reapplied instead of parking until resync.
