"""Suggestion service: `python -m kubeflow_tpu.tuning.service`.

The vizier-core analogue (manager Service on :6789,
kubeflow/katib/vizier.libsonnet:28-380) as a REST JSON service over the
in-repo suggestion algorithms (random/grid/hyperband/bayesianoptimization,
suggestion.libsonnet:3-10 surface):

- ``POST /api/suggestions``  {"algorithm": ..., "parameters": [...],
  "observations": [{"assignments": {...}, "objective": ...}], "count": N}
  → {"suggestions": [{...}, ...]}
- ``GET /api/algorithms``    available algorithm names
- ``GET /healthz``
"""

from __future__ import annotations

import argparse
import json
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.runtime import strip_glog_args
from kubeflow_tpu.tuning.suggestions import (
    _ALGORITHMS,
    Observation,
    domains_from_spec,
    get_algorithm,
)


def suggest(body: dict, default_algorithm: str = "random") -> dict:
    algorithm = body.get("algorithm", default_algorithm)
    parameters = body.get("parameters", [])
    if not parameters:
        raise ValueError("'parameters' must be a non-empty list")
    count = int(body.get("count", 1))
    domains = domains_from_spec(parameters)
    algo = get_algorithm(algorithm, domains, seed=int(body.get("seed", 0)))
    observations = [
        Observation(o["assignments"], float(o["objective"]))
        for o in body.get("observations", [])
    ]
    suggestions = []
    for _ in range(count):
        nxt = algo.next(observations)
        if nxt is None:
            break
        suggestions.append(nxt)
    return {"algorithm": algorithm, "suggestions": suggestions}


def make_server(port: int, default_algorithm: str) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self._send(200, {"status": "ok"})
            elif self.path == "/api/algorithms":
                self._send(200, {"algorithms": sorted(_ALGORITHMS)})
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/api/suggestions":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                self._send(200, suggest(body, default_algorithm))
            except (ValueError, KeyError, TypeError) as e:
                self._send(400, {"error": str(e)})

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="HP suggestion service")
    p.add_argument("--algorithm", default="random",
                   help="default algorithm when a request names none")
    p.add_argument("--port", type=int, default=6789)
    args = p.parse_args(argv)
    httpd = make_server(args.port, args.algorithm)
    print(f"suggestion service ({args.algorithm}) on :{args.port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
