"""Suggestion algorithms — parity with katib's four services
(kubeflow/katib/suggestion.libsonnet:3-10): random, grid, hyperband,
bayesianoptimization. Pure numpy; each algorithm sees completed trials
(assignments + objective) and proposes the next assignments.

Objective convention: algorithms always *maximize*; the controller negates
minimize objectives before feeding observations back.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass

import numpy as np


@dataclass
class Observation:
    assignments: dict[str, object]
    objective: float


@dataclass
class ParamDomain:
    name: str
    type: str  # double | int | categorical | discrete
    space: dict

    def sample(self, rng: np.random.Generator):
        if self.type == "double":
            lo, hi = float(self.space["min"]), float(self.space["max"])
            if self.space.get("logScale"):
                return float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
            return float(rng.uniform(lo, hi))
        if self.type == "int":
            return int(rng.integers(int(self.space["min"]),
                                    int(self.space["max"]) + 1))
        return self.space["list"][rng.integers(len(self.space["list"]))]

    def grid(self, resolution: int):
        if self.type == "double":
            lo, hi = float(self.space["min"]), float(self.space["max"])
            if self.space.get("logScale"):
                return np.exp(
                    np.linspace(np.log(lo), np.log(hi), resolution)
                ).tolist()
            return np.linspace(lo, hi, resolution).tolist()
        if self.type == "int":
            lo, hi = int(self.space["min"]), int(self.space["max"])
            n = min(resolution, hi - lo + 1)
            return sorted({int(round(v)) for v in np.linspace(lo, hi, n)})
        return list(self.space["list"])

    def to_unit(self, value) -> float:
        """Map to [0,1] for the GP."""
        if self.type == "double":
            lo, hi = float(self.space["min"]), float(self.space["max"])
            if self.space.get("logScale"):
                return (math.log(value) - math.log(lo)) / (
                    math.log(hi) - math.log(lo) + 1e-12
                )
            return (value - lo) / (hi - lo + 1e-12)
        if self.type == "int":
            lo, hi = int(self.space["min"]), int(self.space["max"])
            return (value - lo) / max(hi - lo, 1)
        choices = self.space["list"]
        return choices.index(value) / max(len(choices) - 1, 1)

    def from_unit(self, u: float):
        u = float(np.clip(u, 0.0, 1.0))
        if self.type == "double":
            lo, hi = float(self.space["min"]), float(self.space["max"])
            if self.space.get("logScale"):
                return float(
                    math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
                )
            return lo + u * (hi - lo)
        if self.type == "int":
            lo, hi = int(self.space["min"]), int(self.space["max"])
            return int(round(lo + u * (hi - lo)))
        choices = self.space["list"]
        return choices[int(round(u * (len(choices) - 1)))]


def domains_from_spec(parameters: list[dict]) -> list[ParamDomain]:
    return [
        ParamDomain(p["name"], p["parameterType"], p.get("feasibleSpace", {}))
        for p in parameters
    ]


class Suggestion:
    def __init__(self, domains: list[ParamDomain], seed: int = 0):
        self.domains = domains
        self.rng = np.random.default_rng(seed)

    def next(self, observations: list[Observation]) -> dict | None:
        """Next assignments, or None when the space is exhausted."""
        raise NotImplementedError


class RandomSuggestion(Suggestion):
    def next(self, observations):
        return {d.name: d.sample(self.rng) for d in self.domains}


class GridSuggestion(Suggestion):
    def __init__(self, domains, seed=0, resolution: int = 4):
        super().__init__(domains, seed)
        self._grid = list(
            itertools.product(*(d.grid(resolution) for d in domains))
        )

    def next(self, observations):
        tried = {tuple(o.assignments[d.name] for d in self.domains)
                 for o in observations}
        for point in self._grid:
            if point not in tried:
                return dict(zip((d.name for d in self.domains), point))
        return None


class HyperbandSuggestion(Suggestion):
    """Successive-halving: random configs at a small budget, survivors
    promoted with more budget. Budget is surfaced as the reserved parameter
    ``trainingSteps`` the trial template may interpolate."""

    def __init__(self, domains, seed=0, min_budget: int = 10,
                 max_budget: int = 100, eta: int = 3):
        super().__init__(domains, seed)
        self.min_budget = min_budget
        self.max_budget = max_budget
        self.eta = eta

    def next(self, observations):
        # Group observations by budget rung.
        rungs: dict[int, list[Observation]] = {}
        for o in observations:
            rungs.setdefault(
                int(o.assignments.get("trainingSteps", self.min_budget)), []
            ).append(o)
        budget = self.min_budget
        while budget <= self.max_budget:
            at_rung = rungs.get(budget, [])
            # Rung capacity shrinks by eta as budget grows by eta.
            capacity = max(
                1,
                int(self.max_budget / budget / self.eta),
            )
            if len(at_rung) < capacity:
                # Promote the best not-yet-promoted config from the rung
                # below, else sample fresh at the base rung.
                if budget > self.min_budget:
                    below = sorted(
                        rungs.get(budget // self.eta, []),
                        key=lambda o: -o.objective,
                    )
                    promoted_here = {
                        tuple(sorted(
                            (k, v) for k, v in o.assignments.items()
                            if k != "trainingSteps"
                        ))
                        for o in at_rung
                    }
                    for cand in below:
                        key = tuple(sorted(
                            (k, v) for k, v in cand.assignments.items()
                            if k != "trainingSteps"
                        ))
                        if key not in promoted_here:
                            out = dict(cand.assignments)
                            out["trainingSteps"] = budget
                            return out
                if budget == self.min_budget:
                    out = {d.name: d.sample(self.rng) for d in self.domains}
                    out["trainingSteps"] = budget
                    return out
            budget *= self.eta
        # All rungs full: fresh random at base budget.
        out = {d.name: d.sample(self.rng) for d in self.domains}
        out["trainingSteps"] = self.min_budget
        return out


class BayesianSuggestion(Suggestion):
    """GP (RBF kernel) + expected improvement over the unit hypercube."""

    n_init = 3
    n_candidates = 256

    def next(self, observations):
        if len(observations) < self.n_init:
            return {d.name: d.sample(self.rng) for d in self.domains}
        x = np.array([
            [d.to_unit(o.assignments[d.name]) for d in self.domains]
            for o in observations
        ])
        y = np.array([o.objective for o in observations], np.float64)
        y_mean, y_std = y.mean(), y.std() + 1e-9
        yn = (y - y_mean) / y_std

        ls, noise = 0.3, 1e-6
        def kern(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / ls**2)

        k_xx = kern(x, x) + noise * np.eye(len(x))
        chol = np.linalg.cholesky(k_xx)
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, yn))

        cand = self.rng.uniform(size=(self.n_candidates, len(self.domains)))
        k_sx = kern(cand, x)
        mu = k_sx @ alpha
        v = np.linalg.solve(chol, k_sx.T)
        var = np.clip(1.0 - (v**2).sum(0), 1e-12, None)
        sigma = np.sqrt(var)

        best = yn.max()
        z = (mu - best) / sigma
        ei = sigma * (z * _ncdf(z) + _npdf(z))
        u = cand[int(np.argmax(ei))]
        return {
            d.name: d.from_unit(u[i]) for i, d in enumerate(self.domains)
        }


def _ncdf(z):
    return 0.5 * (1 + np.vectorize(math.erf)(z / math.sqrt(2)))


def _npdf(z):
    return np.exp(-0.5 * z**2) / math.sqrt(2 * math.pi)


class TpeSuggestion(Suggestion):
    """Tree-structured Parzen estimator over the unit hypercube.

    Observations are split at the ``gamma`` quantile into good/bad sets;
    each set is modelled as a per-dimension Gaussian mixture (one kernel
    per observation, fixed bandwidth). Candidates are drawn from the good
    mixture and ranked by the density ratio l(x)/g(x) — cheaper than the
    GP (no Cholesky) and robust to non-smooth objectives.
    """

    n_init = 3
    n_candidates = 64
    gamma = 0.25
    bandwidth = 0.15

    def next(self, observations):
        if len(observations) < self.n_init:
            return {d.name: d.sample(self.rng) for d in self.domains}
        x = np.array([
            [d.to_unit(o.assignments[d.name]) for d in self.domains]
            for o in observations
        ])
        y = np.array([o.objective for o in observations], np.float64)
        n_good = max(1, int(math.ceil(self.gamma * len(y))))
        order = np.argsort(-y)
        good, bad = x[order[:n_good]], x[order[n_good:]]
        if not len(bad):
            bad = x

        def mix_logpdf(pts, centers):
            # Independent per-dim Gaussian KDE, mean over kernels.
            d2 = (pts[:, None, :] - centers[None, :, :]) ** 2
            logk = -0.5 * d2 / self.bandwidth**2 - math.log(
                self.bandwidth * math.sqrt(2 * math.pi))
            per_dim = _logmeanexp(logk, axis=1)  # (n_pts, n_dims)
            return per_dim.sum(-1)

        # Sample candidates from the good mixture: pick a kernel, jitter.
        idx = self.rng.integers(len(good), size=self.n_candidates)
        cand = np.clip(
            good[idx] + self.rng.normal(
                0, self.bandwidth, size=(self.n_candidates, x.shape[1])),
            0.0, 1.0)
        score = mix_logpdf(cand, good) - mix_logpdf(cand, bad)
        u = cand[int(np.argmax(score))]
        return {
            d.name: d.from_unit(u[i]) for i, d in enumerate(self.domains)
        }


def _logmeanexp(a, axis):
    m = a.max(axis=axis, keepdims=True)
    return (m + np.log(np.mean(np.exp(a - m), axis=axis, keepdims=True))
            ).squeeze(axis)


class MedianEarlyStop:
    """Early-stop policy in the spirit of Google Vizier's median rule:
    a running trial is stopped when its latest intermediate objective is
    strictly below the median of completed trials' objectives at the same
    (or nearest earlier) step. Maximization convention, like Suggestion.
    """

    def __init__(self, min_trials: int = 3, start_step: int = 1):
        self.min_trials = min_trials
        self.start_step = start_step

    @staticmethod
    def _value_at(curve: list[tuple[int, float]], step: int):
        best = None
        for s, v in curve:
            if s <= step and (best is None or s > best[0]):
                best = (s, v)
        return None if best is None else best[1]

    def should_stop(self, curve: list[tuple[int, float]],
                    completed: list[list[tuple[int, float]]]) -> bool:
        """``curve``/``completed`` are (step, objective) series."""
        if len(completed) < self.min_trials or not curve:
            return False
        step, value = max(curve, key=lambda sv: sv[0])
        if step < self.start_step:
            return False
        peers = [self._value_at(c, step) for c in completed]
        peers = [p for p in peers if p is not None]
        if len(peers) < self.min_trials:
            return False
        return value < float(np.median(peers))


_ALGORITHMS = {
    "random": RandomSuggestion,
    "grid": GridSuggestion,
    "hyperband": HyperbandSuggestion,
    "bayesianoptimization": BayesianSuggestion,
    "tpe": TpeSuggestion,
}


def get_algorithm(name: str, domains: list[ParamDomain],
                  seed: int = 0) -> Suggestion:
    try:
        cls = _ALGORITHMS[name]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; available {sorted(_ALGORITHMS)}"
        )
    return cls(domains, seed)
