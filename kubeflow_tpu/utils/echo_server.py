"""Echo server: `python -m kubeflow_tpu.utils.echo_server`.

Reflects request method/path/headers/body as JSON — the gateway/auth
debugging aid (components/echo-server/echo-server.py analogue).
"""

from __future__ import annotations

import argparse
import json
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.runtime import strip_glog_args


def make_server(port: int) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _echo(self):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length).decode("utf-8", "replace")
            payload = json.dumps({
                "method": self.command,
                "path": self.path,
                "headers": dict(self.headers.items()),
                "body": body,
            }).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        do_GET = do_POST = do_PUT = do_DELETE = _echo

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="echo server")
    p.add_argument("--port", type=int, default=8083)
    args = p.parse_args(argv)
    httpd = make_server(args.port)
    print(f"echo server on :{args.port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
