"""Small infra runtimes: echo server, usage reporter
(components/echo-server, kubeflow/common/spartakus.libsonnet analogues)."""
