"""Minimal 5-field cron schedule parser.

The ScheduledWorkflow controller's trigger clock — the role the reference
delegates to the scheduledworkflow controller's cron library
(/root/reference/kubeflow/pipeline/pipeline-scheduledworkflow.libsonnet).
Standard syntax: ``minute hour day-of-month month day-of-week`` with ``*``,
lists (``1,15``), ranges (``1-5``), and steps (``*/10``, ``8-18/2``).
Day-of-month and day-of-week combine with OR when both are restricted
(POSIX crontab semantics).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass

_BOUNDS = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 7))
_NAMES = ("minute", "hour", "day-of-month", "month", "day-of-week")


def _parse_field(text: str, lo: int, hi: int, name: str) -> frozenset[int]:
    values: set[int] = set()
    for part in text.split(","):
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            try:
                step = int(step_s)
            except ValueError:
                raise ValueError(f"bad step in {name}: {step_s!r}") from None
            if step < 1:
                raise ValueError(f"step must be >=1 in {name}")
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            try:
                start, end = int(a), int(b)
            except ValueError:
                raise ValueError(f"bad range in {name}: {part!r}") from None
        else:
            try:
                start = end = int(part)
            except ValueError:
                raise ValueError(f"bad value in {name}: {part!r}") from None
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise ValueError(
                f"{name} value out of range [{lo},{hi}]: {part!r}"
            )
        values.update(range(start, end + 1, step))
    return frozenset(values)


@dataclass(frozen=True)
class CronSchedule:
    minutes: frozenset[int]
    hours: frozenset[int]
    days: frozenset[int]
    months: frozenset[int]
    weekdays: frozenset[int]
    # POSIX: when both day fields are restricted, either may match.
    dom_restricted: bool
    dow_restricted: bool

    @classmethod
    def parse(cls, expr: str) -> "CronSchedule":
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(
                f"cron needs 5 fields (minute hour dom month dow), "
                f"got {len(fields)}: {expr!r}"
            )
        parsed = [
            _parse_field(f, lo, hi, name)
            for f, (lo, hi), name in zip(fields, _BOUNDS, _NAMES)
        ]
        # Vixie cron accepts both 0 and 7 for Sunday.
        parsed[4] = frozenset(0 if v == 7 else v for v in parsed[4])
        return cls(*parsed, dom_restricted=fields[2] != "*",
                   dow_restricted=fields[4] != "*")

    def matches(self, dt: datetime.datetime) -> bool:
        # cron weekday: 0=Sunday; datetime.weekday(): 0=Monday (see
        # _day_matches for the conversion and the POSIX dom/dow OR rule).
        return (dt.minute in self.minutes and dt.hour in self.hours
                and dt.month in self.months and self._day_matches(dt))

    def _day_matches(self, dt: datetime.datetime) -> bool:
        dom_ok = dt.day in self.days
        dow_ok = (dt.weekday() + 1) % 7 in self.weekdays
        if self.dom_restricted and self.dow_restricted:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def next_fire(self, after: datetime.datetime) -> datetime.datetime:
        """First matching minute strictly after ``after`` (seconds
        truncated). Scans by day with direct hour/minute enumeration —
        any valid schedule fires within 4 years (covers Feb 29)."""
        start = after.replace(second=0, microsecond=0)
        start += datetime.timedelta(minutes=1)
        day = start.replace(hour=0, minute=0)
        limit = after + datetime.timedelta(days=4 * 366)
        while day <= limit:
            if day.month not in self.months:
                year = day.year + (day.month == 12)
                day = day.replace(year=year, month=day.month % 12 + 1,
                                  day=1)
                continue
            if self._day_matches(day):
                for hour in sorted(self.hours):
                    for minute in sorted(self.minutes):
                        cand = day.replace(hour=hour, minute=minute)
                        if cand >= start:
                            return cand
            day += datetime.timedelta(days=1)
        raise ValueError("no matching time within 4 years")
