"""Anonymous usage reporter: `python -m kubeflow_tpu.utils.usage_reporter`.

The spartakus analogue (kubeflow/common/spartakus.libsonnet:1-122,
opt-out warning at coordinator.usageReportWarn, coordinator.go:201). Reports
an anonymous cluster id + platform version on an interval. Disabled reporting
(`--enabled=false`) still runs the loop but only logs locally — the container
stays healthy either way, and nothing is ever sent unless a report URL is
explicitly configured.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
import urllib.request

from kubeflow_tpu.runtime import strip_glog_args
from kubeflow_tpu.version import __version__

log = logging.getLogger(__name__)


def build_report(usage_id: str) -> dict:
    return {
        "usage_id": usage_id,
        "platform": "kubeflow-tpu",
        "version": __version__,
        "timestamp": int(time.time()),
    }


def report_once(usage_id: str, enabled: bool, report_url: str,
                *, log_fn=log.info) -> bool:
    report = build_report(usage_id)
    if not enabled or not report_url:
        log_fn("usage reporting disabled; report (not sent): %s",
               json.dumps(report))
        return False
    try:
        req = urllib.request.Request(
            report_url, json.dumps(report).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            ok = 200 <= resp.status < 300
    except OSError as e:
        log_fn("usage report failed: %s", e)
        return False
    return ok


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="anonymous usage reporter")
    p.add_argument("--usage-id", default="unknown")
    p.add_argument("--enabled", default="false",
                   help="true/false — off by default (opt-in)")
    p.add_argument("--report-url", default="",
                   help="endpoint to POST reports to (empty = log only)")
    p.add_argument("--interval", type=float, default=3600.0)
    p.add_argument("--once", action="store_true")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    enabled = str(args.enabled).lower() in ("true", "1", "yes")
    if args.once:
        report_once(args.usage_id, enabled, args.report_url)
        return 0
    try:
        while True:
            report_once(args.usage_id, enabled, args.report_url)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
