"""Static analysis gate — the platform's own lint engine.

The reference gates every PR with flake8 + boilerplate checks
(testing/test_flake8.py, scripts/check_boilerplate-style gates); this image
ships no linter and the platform must not depend on one being installed, so
the gate is implemented here on the stdlib ``ast``/``tokenize`` machinery.
``tests/test_lint.py`` runs it over the whole repo; ``python -m
kubeflow_tpu.utils.lint [paths]`` runs it from the command line / CI
workflow.

Checks (each maps to a flake8 family):
- E9  syntax errors (the file must parse)
- E501 line too long (default 100, URLs in comments exempt)
- W291/W293 trailing whitespace
- W191 tabs in indentation
- F401 unused imports (module scope; ``__init__.py`` re-exports and
  ``# noqa`` lines exempt)
- E711 comparisons to None with ==/!=
- E722 bare ``except:``
- D100 missing module docstring (the boilerplate-check analogue: every
  module must say what it is)
- F821 undefined names (any Load of a name never bound anywhere in the
  file, an import, a builtin, or a module dunder — the typo catcher;
  deliberately file-flat rather than scope-exact, so it under-reports
  scope leaks but never false-positives on conditional definitions)
- F841 unused local variables (assigned in a function, never read in
  that function or its nested scopes; ``_``-prefixed and tuple-unpacked
  names exempt)
- A001 shadowed builtins (a function/class/argument/assignment binding
  that hides a Python builtin)
"""

from __future__ import annotations

import ast
import sys
import tokenize
from dataclasses import dataclass
from pathlib import Path


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


MAX_LINE = 100


def _noqa_lines(source: str) -> set[int]:
    out = set()
    for i, line in enumerate(source.splitlines(), 1):
        if "# noqa" in line:
            out.add(i)
    return out


def _check_lines(path: str, source: str, noqa: set[int]) -> list[Violation]:
    out = []
    for i, line in enumerate(source.splitlines(), 1):
        if i in noqa:
            continue
        stripped = line.rstrip("\n")
        if len(stripped) > MAX_LINE and "http" not in stripped:
            out.append(Violation(path, i, "E501",
                                 f"line too long ({len(stripped)} > "
                                 f"{MAX_LINE})"))
        if stripped != stripped.rstrip():
            out.append(Violation(path, i, "W291", "trailing whitespace"))
        indent = stripped[: len(stripped) - len(stripped.lstrip())]
        if "\t" in indent:
            out.append(Violation(path, i, "W191", "tab in indentation"))
    return out


class _ImportTracker(ast.NodeVisitor):
    """Module-scope import bindings vs names used anywhere in the file."""

    def __init__(self) -> None:
        self.imports: dict[str, tuple[int, str]] = {}  # binding -> (line, desc)
        self.used: set[str] = set()
        self._depth = 0

    def visit_Import(self, node: ast.Import) -> None:
        if self._depth == 0:
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                self.imports[name] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":  # compiler directive, never "used"
            return
        if self._depth == 0:
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                self.imports[name] = (node.lineno, alias.name)

    def _scoped(self, node) -> None:
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)


def _check_ast(path: str, source: str, noqa: set[int]) -> list[Violation]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Violation(path, e.lineno or 0, "E999",
                          f"syntax error: {e.msg}")]
    out = []

    if not (Path(path).name == "__init__.py" and not source.strip()):
        doc = ast.get_docstring(tree)
        if not doc:
            out.append(Violation(path, 1, "D100",
                                 "missing module docstring"))

    is_init = Path(path).name == "__init__.py"
    if not is_init:  # __init__ re-exports bind names for importers
        tracker = _ImportTracker()
        tracker.visit(tree)
        # Names exported via __all__ strings count as used.
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in node.targets)):
                for elt in ast.walk(node.value):
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        tracker.used.add(elt.value)
        for name, (line, desc) in tracker.imports.items():
            if name not in tracker.used and line not in noqa:
                out.append(Violation(path, line, "F401",
                                     f"'{desc}' imported but unused"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Compare) and node.lineno not in noqa:
            for op, comp in zip(node.ops, node.comparators):
                if (isinstance(op, (ast.Eq, ast.NotEq))
                        and isinstance(comp, ast.Constant)
                        and comp.value is None):
                    out.append(Violation(
                        path, node.lineno, "E711",
                        "comparison to None should be 'is None'"))
        if (isinstance(node, ast.ExceptHandler) and node.type is None
                and node.lineno not in noqa):
            out.append(Violation(path, node.lineno, "E722",
                                 "bare 'except:'"))
    out.extend(_check_undefined(path, tree, noqa))
    out.extend(_check_unused_locals(path, tree, noqa))
    out.extend(_check_shadowed_builtins(path, tree, noqa))
    return out


_MODULE_DUNDERS = {
    "__name__", "__file__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__annotations__",
    "__dict__", "__class__", "__path__",
}

# The full builtin namespace: F821's known-name floor, and (non-dunder
# members) the A001 shadowing set — `id`/`input`/`type` ARE flagged,
# they are the classic shadowing bugs.
_BUILTIN_NAMES = set(dir(__import__("builtins")))


def _bound_names(tree: ast.AST) -> set[str]:
    """Every name bound anywhere in the file, in any scope: imports,
    assignments, defs, args, loop/with/except/comprehension targets,
    globals, walrus, match captures."""
    bound: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name == "*":
                    bound.add("*")  # star import: F821 bails on the file
                else:
                    bound.add(alias.asname
                              or alias.name.split(".")[0])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, ast.arg):
            bound.add(node.arg)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            bound.update(node.names)
        elif isinstance(node, ast.MatchAs) and node.name:
            bound.add(node.name)
        elif isinstance(node, ast.MatchStar) and node.name:
            bound.add(node.name)
        elif isinstance(node, ast.MatchMapping) and node.rest:
            bound.add(node.rest)
    return bound


def _check_undefined(path: str, tree: ast.AST,
                     noqa: set[int]) -> list[Violation]:
    bound = _bound_names(tree)
    if "*" in bound:  # star import makes the name universe unknowable
        return []
    known = bound | _BUILTIN_NAMES | _MODULE_DUNDERS
    out = []
    seen: set[tuple[str, int]] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
                and node.id not in known
                and node.lineno not in noqa
                and (node.id, node.lineno) not in seen):
            seen.add((node.id, node.lineno))
            out.append(Violation(path, node.lineno, "F821",
                                 f"undefined name '{node.id}'"))
    return out


def _own_scope_nodes(fn: ast.AST):
    """Walk a function's OWN scope in source order (F841 reports the
    FIRST assignment line): descend everywhere except into nested
    function/class definitions (their bindings are theirs)."""
    import collections

    queue = collections.deque(ast.iter_child_nodes(fn))
    while queue:
        node = queue.popleft()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            queue.extend(ast.iter_child_nodes(node))


def _check_unused_locals(path: str, tree: ast.AST,
                         noqa: set[int]) -> list[Violation]:
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        assigned: dict[str, int] = {}
        # Bindings belong to the function's own scope (class attributes
        # and nested defs' locals are not this function's locals)...
        for node in _own_scope_nodes(fn):
            if isinstance(node, ast.Assign):
                # flake8 parity: only simple single-target assignments
                # count (tuple unpacking often carries intentional
                # discards).
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    name = node.targets[0].id
                    if not name.startswith("_"):
                        assigned.setdefault(name, node.lineno)
        # ...but reads anywhere inside (closures included) count as use.
        loaded: set[str] = set()
        escaping: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load):
                loaded.add(node.id)
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                escaping.update(node.names)
        for name, line in assigned.items():
            if (name not in loaded and name not in escaping
                    and line not in noqa):
                out.append(Violation(
                    path, line, "F841",
                    f"local variable '{name}' is assigned to but never "
                    "used"))
    return out


def _check_shadowed_builtins(path: str, tree: ast.AST,
                             noqa: set[int]) -> list[Violation]:
    """A001: builtin shadowing in NAME scopes (module globals, function
    locals, arguments, def/class names). Class attributes and methods
    are exempt — they live behind ``self.``/``cls.`` and shadow nothing
    (the A003 family, which flake8-builtins users near-universally
    disable)."""
    out = []

    def flag(name: str, line: int, what: str) -> None:
        if (name in _BUILTIN_NAMES and not name.startswith("_")
                and line not in noqa):
            out.append(Violation(path, line, "A001",
                                 f"{what} '{name}' shadows a builtin"))

    def visit(node: ast.AST, in_class_body: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                if not in_class_body:  # methods are class attributes
                    flag(child.name, child.lineno, "function")
                args = child.args
                for a in (args.posonlyargs + args.args + args.kwonlyargs
                          + ([args.vararg] if args.vararg else [])
                          + ([args.kwarg] if args.kwarg else [])):
                    if a.arg not in ("self", "cls"):
                        flag(a.arg, a.lineno, "argument")
                visit(child, in_class_body=False)
            elif isinstance(child, ast.ClassDef):
                if not in_class_body:
                    flag(child.name, child.lineno, "class")
                visit(child, in_class_body=True)
            elif (isinstance(child, ast.Name)
                  and isinstance(child.ctx, ast.Store)
                  and not in_class_body):
                flag(child.id, child.lineno, "assignment to")
                visit(child, in_class_body)
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                for alias in child.names:
                    bound = alias.asname or (
                        alias.name.split(".")[0]
                        if isinstance(child, ast.Import) else alias.name
                    )
                    if bound != "*":
                        flag(bound, child.lineno, "import binding")
            elif isinstance(child, ast.ExceptHandler):
                if child.name:
                    flag(child.name, child.lineno, "except binding")
                visit(child, in_class_body)
            elif isinstance(child, ast.Lambda):
                for a in child.args.args:
                    flag(a.arg, a.lineno, "argument")
                visit(child, in_class_body=False)
            else:
                # Expressions/statements keep the surrounding binding
                # context (a class-body `x = ...` RHS may contain
                # comprehensions whose targets are still exempt enough).
                visit(child, in_class_body)

    visit(tree, in_class_body=False)
    return out


def lint_file(path: str | Path) -> list[Violation]:
    path = Path(path)
    try:
        with tokenize.open(path) as f:
            source = f.read()
    except (OSError, UnicodeDecodeError, SyntaxError) as e:
        return [Violation(str(path), 0, "E902", str(e))]
    noqa = _noqa_lines(source)
    return (_check_lines(str(path), source, noqa)
            + _check_ast(str(path), source, noqa))


EXCLUDE_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist"}


def lint_tree(*roots: str | Path) -> list[Violation]:
    out = []
    for root in roots:
        root = Path(root)
        if root.is_file():
            out.extend(lint_file(root))
            continue
        for path in sorted(root.rglob("*.py")):
            if any(part in EXCLUDE_DIRS for part in path.parts):
                continue
            out.extend(lint_file(path))
    return out


def main(argv: list[str] | None = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    roots = args or ["."]
    violations = lint_tree(*roots)
    for v in violations:
        print(v)
    print(f"{len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
