"""BenchmarkJob controller — run a job template N times, aggregate metrics."""

from __future__ import annotations

import copy

from kubeflow_tpu.apis.benchmark import (
    BENCHMARK_API_VERSION,
    BENCHMARK_JOB_KIND,
)
from kubeflow_tpu.apis.jobs import JOBS_API_VERSION
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.operators.base import Controller

LABEL_BENCHMARK = "kubeflow-tpu.org/benchmark-name"


class BenchmarkJobController(Controller):
    api_version = BENCHMARK_API_VERSION
    kind = BENCHMARK_JOB_KIND
    resync_seconds = 10.0

    def watched_kinds(self):
        return [(JOBS_API_VERSION, "JaxJob")]

    def reconcile(self, bench: dict) -> None:
        bench = copy.deepcopy(bench)
        spec = bench["spec"]
        status = bench.setdefault("status", {})
        if status.get("state") in ("Succeeded", "Failed"):
            return
        status.setdefault("state", "Running")
        runs = status.setdefault("runs", [])
        ns = bench["metadata"]["namespace"]
        reps = spec.get("repetitions", 1)
        wanted = spec.get("metrics", ["samples_per_sec"])

        # Collect finished runs.
        for run in runs:
            if run["state"] in ("Succeeded", "Failed"):
                continue
            job = self.client.get_or_none(
                JOBS_API_VERSION, spec["jobTemplate"].get("kind", "JaxJob"),
                run["jobName"], ns,
            )
            if job is None:
                continue
            jstate = job.get("status", {}).get("state")
            if jstate in ("Succeeded", "Failed"):
                run["state"] = jstate
                metrics = job.get("status", {}).get("metrics", {})
                run["metrics"] = {
                    m: metrics[m] for m in wanted if m in metrics
                }

        finished = [r for r in runs if r["state"] in ("Succeeded", "Failed")]
        if any(r["state"] == "Failed" for r in finished):
            status["state"] = "Failed"
        elif len(finished) >= reps:
            status["state"] = "Succeeded"
            status["results"] = self._aggregate(finished, wanted)
        elif len(runs) == len(finished):
            self._spawn_run(bench, runs)
        self._push_status(bench)

    def _aggregate(self, runs: list[dict], wanted: list[str]) -> dict:
        results = {}
        for m in wanted:
            values = [r["metrics"][m] for r in runs if m in r.get("metrics", {})]
            if values:
                results[m] = {
                    "mean": sum(values) / len(values),
                    "min": min(values),
                    "max": max(values),
                    "runs": len(values),
                }
        return results

    def _spawn_run(self, bench: dict, runs: list[dict]) -> None:
        index = len(runs)
        name = f"{bench['metadata']['name']}-run-{index}"
        job = copy.deepcopy(bench["spec"]["jobTemplate"])
        job.setdefault("apiVersion", JOBS_API_VERSION)
        job.setdefault("kind", "JaxJob")
        meta = job.setdefault("metadata", {})
        meta["name"] = name
        meta["namespace"] = bench["metadata"]["namespace"]
        meta.setdefault("labels", {})[LABEL_BENCHMARK] = (
            bench["metadata"]["name"]
        )
        meta["ownerReferences"] = [k8s.object_ref(bench)]
        self.client.create(job)
        runs.append({"index": index, "jobName": name, "state": "Running"})

    def _push_status(self, bench: dict) -> None:
        current = self.client.get_or_none(
            self.api_version, self.kind, bench["metadata"]["name"],
            bench["metadata"]["namespace"],
        )
        if current is not None and current.get("status") != bench["status"]:
            current["status"] = bench["status"]
            self.client.update_status(current)
