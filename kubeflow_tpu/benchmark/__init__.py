"""Benchmark runtime (the kubebench analogue).

kubebench runs benchmark workflows via its operator and records reporter CSVs
(kubeflow/kubebench/prototypes/kubebench-job.jsonnet:6-23). Here a
BenchmarkJob CR wraps a job template; the controller runs it (optionally N
repetitions), harvests the metrics each run publishes into job status, and
aggregates results in the BenchmarkJob status.
"""

from kubeflow_tpu.benchmark.controller import BenchmarkJobController

__all__ = ["BenchmarkJobController"]
