"""Unified metrics: ONE registry, ONE Prometheus exposition renderer.

Every ``/metrics``-shaped surface in the platform — the model server's
``/monitoring/prometheus/metrics``, the gateway admin port, every manager
binary's :class:`kubeflow_tpu.runtime.HealthServer`, the availability
prober, the bootstrapper — renders through this module. It is the
platform's promhttp: before it, four hand-rolled renderers each knew the
text format (and one of them typed every gauge as a counter); now exactly
one place does, which is the grep-able invariant the CI exposition lint
(:mod:`kubeflow_tpu.observability.lint`) enforces.

Three instrument kinds, all thread-safe and optionally labeled:

- :class:`Counter` — monotone float/int, ``inc()``;
- :class:`Gauge` — settable value or a ``set_function`` sampled at
  render time (queue depths, pool sizes);
- :class:`Histogram` — fixed log-spaced latency buckets by default,
  ``_bucket``/``_sum``/``_count`` exposition, and in-process quantile
  estimation (``quantile(0.99)``) so callers can publish p50/p99 without
  a scrape round-trip.

The legacy ``render_prometheus(dict)`` helper (names ending ``_total``
typed counter, everything else gauge) lives here too — the dict-shaped
exporters (prober, bootstrapper, HealthServer ``metrics_fn``) ride it.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Callable, Iterable

# Log-spaced latency bounds, 100 microseconds to 100 seconds, four per
# decade — wide enough for a sub-ms decode dispatch and a minute-long
# straggler request to land in *interior* buckets of the same family.
DEFAULT_LATENCY_BUCKETS: tuple[float, ...] = tuple(
    round(1e-4 * 10 ** (i / 4), 10) for i in range(25)
)


def escape_label_value(value) -> str:
    """Escape a label value per the exposition format (backslash, quote,
    newline) — the reason free-form strings (model names, error text) are
    safe to use as labels."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def type_line(name: str, kind: str) -> str:
    """The ``# TYPE`` header for a family. Exported so tests and tools can
    assert on exposition output without duplicating the literal — keeping
    this module the only place in the tree that spells the text format."""
    return f"# TYPE {name} {kind}\n"


def _fmt(value) -> str:
    if isinstance(value, float):
        return f"{value:.6f}"
    return str(value)


def _fmt_bound(bound: float) -> str:
    return format(bound, ".6g")


def _label_str(names: tuple[str, ...], values: tuple[str, ...],
               extra: str = "") -> str:
    pairs = [f'{n}="{escape_label_value(v)}"'
             for n, v in zip(names, values)]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render_prometheus(metrics: dict) -> str:
    """Render name→value pairs in Prometheus exposition format.

    Names ending in ``_total`` are typed ``counter``, everything else
    ``gauge`` — the shared rendering rule for every dict-shaped exporter
    in the platform, so there is exactly one place that knows the text
    format."""
    out = []
    for name, value in metrics.items():
        kind = "counter" if name.endswith("_total") else "gauge"
        out.append(f"{type_line(name, kind)}{name} {_fmt(value)}\n")
    return "".join(out)


class Counter:
    """Monotonically increasing value. ``inc`` with a negative amount
    raises — a counter that goes down is a gauge wearing a disguise."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value: float = 0

    def inc(self, amount: float = 1) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Settable value, or a callback sampled at render time."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value: float = 0
        self._fn: Callable[[], float] | None = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value
            self._fn = None

    def inc(self, amount: float = 1) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1) -> None:
        with self._lock:
            self._value -= amount

    def set_function(self, fn: Callable[[], float]) -> None:
        """Sample ``fn`` at every read — the render-time source for values
        that already live somewhere (queue lengths, pool occupancy)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self):
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        return fn()


class Histogram:
    """Fixed-bucket histogram with cumulative exposition and in-process
    quantile estimation.

    Buckets are *upper bounds* (strictly increasing); an implicit +Inf
    bucket catches the overflow. ``observe`` is a lock + bisect — cheap
    enough for per-token hot paths. Usable standalone (the train loop's
    step-time histogram) or through a :class:`MetricRegistry` family.
    """

    def __init__(self, buckets: Iterable[float] | None = None) -> None:
        bounds = tuple(sorted(set(buckets if buckets is not None
                                  else DEFAULT_LATENCY_BUCKETS)))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: the +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    @property
    def bounds(self) -> tuple[float, ...]:
        return self._bounds

    def observe(self, value: float) -> None:
        idx = bisect_left(self._bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """(cumulative counts per bound + the +Inf total, sum, count) —
        one consistent view, the render/lint unit."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total = self._sum, self._count
        cumulative = []
        running = 0
        for c in counts:
            running += c
            cumulative.append(running)
        return cumulative, total_sum, total

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0..1) by linear interpolation within
        the bucket holding the target rank — the promql
        ``histogram_quantile`` estimate, computed in-process."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        lower = 0.0
        for bound, c in zip(self._bounds, counts[:-1]):
            if cum + c >= target and c > 0:
                frac = (target - cum) / c
                return lower + (bound - lower) * frac
            cum += c
            lower = bound
        # Rank falls in the +Inf bucket: the top finite bound is the best
        # (under-)estimate available.
        return self._bounds[-1]


class _Family:
    """One named metric family: kind + label names + children per label
    tuple. Unlabeled families proxy the instrument methods directly, so
    ``registry.counter("x_total").inc()`` needs no ``.labels()`` hop."""

    def __init__(self, name: str, kind: str, help_text: str,
                 labelnames: tuple[str, ...], factory: Callable) -> None:
        self.name = name
        self.kind = kind
        self.help = help_text
        self.labelnames = labelnames
        self._factory = factory
        self._children: dict[tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, *values):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(key)} label values for "
                f"{len(self.labelnames)} label names {self.labelnames}")
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._factory()
                self._children[key] = child
            return child

    def children(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    # Unlabeled conveniences (delegate to the single anonymous child).
    def inc(self, amount: float = 1) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1) -> None:
        self.labels().dec(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self.labels().set_function(fn)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def quantile(self, q: float) -> float:
        return self.labels().quantile(q)

    @property
    def value(self):
        return self.labels().value


class MetricRegistry:
    """Thread-safe family registry + the exposition renderer.

    Re-registering a name returns the existing family (so any module can
    say ``registry.counter("x_total")`` without ordering constraints);
    re-registering with a different kind or label set raises — the scrape
    contract for a name must be stable."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help_text: str,
                labels: Iterable[str], factory: Callable) -> _Family:
        labelnames = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered as {kind}"
                        f"{labelnames}, but exists as {fam.kind}"
                        f"{fam.labelnames}")
                return fam
            fam = _Family(name, kind, help_text, labelnames, factory)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str = "",
                labels: Iterable[str] = ()) -> _Family:
        return self._family(name, "counter", help_text, labels, Counter)

    def gauge(self, name: str, help_text: str = "",
              labels: Iterable[str] = ()) -> _Family:
        return self._family(name, "gauge", help_text, labels, Gauge)

    def histogram(self, name: str, help_text: str = "",
                  labels: Iterable[str] = (),
                  buckets: Iterable[float] | None = None) -> _Family:
        bounds = (tuple(buckets) if buckets is not None
                  else DEFAULT_LATENCY_BUCKETS)
        return self._family(name, "histogram", help_text, labels,
                            lambda: Histogram(bounds))

    def render(self) -> str:
        """Full exposition for every family: ``# HELP``/``# TYPE`` once
        per family, then every child's samples, label values escaped."""
        with self._lock:
            families = list(self._families.values())
        out: list[str] = []
        for fam in families:
            if fam.help:
                out.append(f"# HELP {fam.name} "
                           f"{escape_label_value(fam.help)}\n")
            out.append(type_line(fam.name, fam.kind))
            for key, child in fam.children():
                labels = _label_str(fam.labelnames, key)
                if fam.kind == "histogram":
                    cumulative, total_sum, total = child.snapshot()
                    bounds = [*map(_fmt_bound, child.bounds), "+Inf"]
                    for le, cum in zip(bounds, cumulative):
                        lstr = _label_str(fam.labelnames, key,
                                          extra=f'le="{le}"')
                        out.append(f"{fam.name}_bucket{lstr} {cum}\n")
                    out.append(f"{fam.name}_sum{labels} "
                               f"{_fmt(total_sum)}\n")
                    out.append(f"{fam.name}_count{labels} {total}\n")
                else:
                    out.append(f"{fam.name}{labels} "
                               f"{_fmt(child.value)}\n")
        return "".join(out)
