"""Availability prober: `python -m kubeflow_tpu.observability.collector`.

Probes a platform endpoint on an interval and exports the
`kubeflow_availability` prometheus gauge on :8000 — the metric-collector
contract (metric-collector/service-readiness/kubeflow-readiness.py:21-37,
deployed by kubeflow/gcp/prototypes/metric-collector.jsonnet).
"""

from __future__ import annotations

import argparse
import logging
import sys
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.runtime import strip_glog_args

log = logging.getLogger(__name__)


class AvailabilityProber:
    def __init__(self, target_url: str, interval: float = 30.0,
                 timeout: float = 10.0):
        self.target_url = target_url
        self.interval = interval
        self.timeout = timeout
        self.available = 0
        self.probes_total = 0
        self.failures_total = 0
        self._stop = threading.Event()

    def probe_once(self) -> bool:
        self.probes_total += 1
        try:
            with urllib.request.urlopen(self.target_url,
                                        timeout=self.timeout) as resp:
                ok = 200 <= resp.status < 400
        except (urllib.error.URLError, OSError, ValueError):
            ok = False
        self.available = int(ok)
        if not ok:
            self.failures_total += 1
        return ok

    def run(self) -> None:
        while not self._stop.is_set():
            ok = self.probe_once()
            log.info("probe %s: %s", self.target_url,
                     "up" if ok else "DOWN")
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()

    def render_metrics(self) -> str:
        return (
            "# TYPE kubeflow_availability gauge\n"
            f"kubeflow_availability {self.available}\n"
            "# TYPE kubeflow_availability_probes_total counter\n"
            f"kubeflow_availability_probes_total {self.probes_total}\n"
            "# TYPE kubeflow_availability_failures_total counter\n"
            f"kubeflow_availability_failures_total {self.failures_total}\n"
        )


def make_server(prober: AvailabilityProber, port: int) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/metrics":
                body = prober.render_metrics().encode()
            elif self.path in ("/healthz", "/readyz"):
                body = b'{"status":"ok"}'
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="kubeflow availability prober")
    p.add_argument("--target-url", required=True)
    p.add_argument("--interval", type=float, default=30.0)
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--once", action="store_true",
                   help="probe once, print the gauge, exit 0/1")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    prober = AvailabilityProber(args.target_url, args.interval)
    if args.once:
        ok = prober.probe_once()
        print(prober.render_metrics(), end="")
        return 0 if ok else 1
    httpd = make_server(prober, args.port)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        prober.run()
    except KeyboardInterrupt:
        pass
    finally:
        prober.stop()
        httpd.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
