"""Availability prober: `python -m kubeflow_tpu.observability.collector`.

Probes a platform endpoint on an interval and exports the
`kubeflow_availability` prometheus gauge on :8000 — the metric-collector
contract (metric-collector/service-readiness/kubeflow-readiness.py:21-37,
deployed by kubeflow/gcp/prototypes/metric-collector.jsonnet). Like the
reference prober — which exchanges a service-account key for a Google
id-token and probes *through* IAP — this prober can exchange a platform
service-account key at the gatekeeper's /token endpoint and send the
resulting Bearer id-token, so it measures availability of the
authenticated front door, not of an auth bypass.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# Re-exported for the exporters (and tests) that historically imported
# the renderer from here; the implementation — the ONE place that knows
# the exposition text format — now lives in observability.metrics.
from kubeflow_tpu.observability.metrics import render_prometheus
from kubeflow_tpu.runtime import strip_glog_args

log = logging.getLogger(__name__)


class TokenClient:
    """Service-account id-token supply for the prober.

    Exchanges ``{service_account, key}`` at the gatekeeper's /token
    endpoint; tokens are cached and refreshed ``refresh_margin`` seconds
    before expiry (kubeflow-readiness.py:21-37's
    get_google_open_id_connect_token role).
    """

    def __init__(self, token_url: str, service_account: str, key: str, *,
                 audience: str = "", timeout: float = 10.0,
                 refresh_margin: float = 60.0):
        self.token_url = token_url
        self.service_account = service_account
        self.key = key
        self.audience = audience
        self.timeout = timeout
        self.refresh_margin = refresh_margin
        self._token = ""
        self._expires_at = 0.0
        self._lock = threading.Lock()

    def invalidate(self) -> None:
        with self._lock:
            self._expires_at = 0.0

    def token(self) -> str:
        """Current id-token, fetching/refreshing as needed. Raises
        OSError/ValueError on exchange failure (a probe through a broken
        token path must count as DOWN, not silently go unauthenticated).

        The exchange itself runs OUTSIDE the lock: holding it across
        the HTTP round-trip made every concurrent token() caller queue
        behind one slow/hung gatekeeper for up to ``timeout`` seconds
        (tpu-lint lock-blocking-call, the PR-9 stall class). Two
        racing callers may both exchange; both land valid tokens and
        last-writer-wins is harmless."""
        with self._lock:
            if self._token and time.time() < (self._expires_at
                                              - self.refresh_margin):
                return self._token
        body = {"service_account": self.service_account,
                "key": self.key}
        if self.audience:
            body["audience"] = self.audience
        req = urllib.request.Request(
            self.token_url, method="POST",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            grant = json.loads(resp.read())
        token = grant.get("id_token") if isinstance(grant, dict) \
            else None
        if not token:
            raise ValueError("token response missing id_token")
        try:
            ttl = float(grant.get("expires_in", 3600))
        except (TypeError, ValueError):
            ttl = 3600.0
        with self._lock:
            self._token = token
            self._expires_at = time.time() + ttl
            return self._token


class AvailabilityProber:
    def __init__(self, target_url: str, interval: float = 30.0,
                 timeout: float = 10.0,
                 token_client: TokenClient | None = None):
        self.target_url = target_url
        self.interval = interval
        self.timeout = timeout
        self.token_client = token_client
        self.available = 0
        self.probes_total = 0
        self.failures_total = 0
        self._stop = threading.Event()

    def _fetch(self) -> bool:
        req = urllib.request.Request(self.target_url, method="GET")
        if self.token_client is not None:
            req.add_header("Authorization",
                           f"Bearer {self.token_client.token()}")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return 200 <= resp.status < 400

    def probe_once(self) -> bool:
        self.probes_total += 1
        try:
            ok = self._fetch()
        except urllib.error.HTTPError as e:
            ok = False
            if e.code == 401 and self.token_client is not None:
                # Key may have rotated under us: one fresh-token retry.
                self.token_client.invalidate()
                try:
                    ok = self._fetch()
                except (urllib.error.URLError, OSError, ValueError):
                    ok = False
        except (urllib.error.URLError, OSError, ValueError):
            ok = False
        self.available = int(ok)
        if not ok:
            self.failures_total += 1
        return ok

    def run(self) -> None:
        while not self._stop.is_set():
            ok = self.probe_once()
            log.info("probe %s: %s", self.target_url,
                     "up" if ok else "DOWN")
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()

    def render_metrics(self) -> str:
        return render_prometheus({
            "kubeflow_availability": self.available,
            "kubeflow_availability_probes_total": self.probes_total,
            "kubeflow_availability_failures_total": self.failures_total,
        })


def make_server(prober: AvailabilityProber, port: int) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/metrics":
                body = prober.render_metrics().encode()
            elif self.path in ("/healthz", "/readyz"):
                body = b'{"status":"ok"}'
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="kubeflow availability prober")
    p.add_argument("--target-url", required=True)
    p.add_argument("--interval", type=float, default=30.0)
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--once", action="store_true",
                   help="probe once, print the gauge, exit 0/1")
    p.add_argument("--token-url", default="",
                   help="gatekeeper /token endpoint; set with "
                        "--service-account to probe through the "
                        "authenticated front door")
    p.add_argument("--service-account", default="",
                   help="platform service-account name for the id-token "
                        "grant")
    p.add_argument("--sa-key-file", default="",
                   help="file holding the service-account key")
    p.add_argument("--audience", default="",
                   help="aud claim to request (default: issuer default)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    token_client = None
    if args.token_url and args.service_account:
        key = ""
        if args.sa_key_file:
            with open(args.sa_key_file) as f:
                key = f.read().strip()
        token_client = TokenClient(args.token_url, args.service_account,
                                   key, audience=args.audience)
    prober = AvailabilityProber(args.target_url, args.interval,
                                token_client=token_client)
    if args.once:
        ok = prober.probe_once()
        print(prober.render_metrics(), end="")
        return 0 if ok else 1
    httpd = make_server(prober, args.port)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        prober.run()
    except KeyboardInterrupt:
        pass
    finally:
        prober.stop()
        httpd.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
