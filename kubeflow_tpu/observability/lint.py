"""Promtool-style Prometheus exposition checker (pure python).

``lint(text)`` validates a scraped ``/metrics`` body against the
exposition format the platform's single renderer
(:mod:`kubeflow_tpu.observability.metrics`) is supposed to emit:

- TYPE header lines well-formed, known kinds, at most one per family;
- every sample belongs to a family declared BEFORE it (the bug class
  this exists for: the old HealthServer typed every gauge ``counter``);
- counter families named ``*_total``;
- metric/label names legal, label values quoted with only legal escapes;
- histogram series: ``le`` bounds strictly increasing, cumulative counts
  non-decreasing, a ``+Inf`` bucket present and equal to ``_count``,
  ``_sum``/``_count`` present.

``python -m kubeflow_tpu.observability.lint --self-check`` is the CI
stage (ci/metrics_lint.sh): it boots the model server, the gateway
admin port, the availability prober and an operator HealthServer
in-process, scrapes each endpoint over real HTTP, and fails on any
violation — so a renderer regression can't reach a real Prometheus.
"""

from __future__ import annotations

import re
import sys
import urllib.request

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")
_KINDS = {"counter", "gauge", "histogram", "summary", "untyped"}
# Histogram/summary component suffixes resolve to their base family.
_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_value(token: str) -> float:
    if token in ("+Inf", "Inf"):
        return float("inf")
    if token == "-Inf":
        return float("-inf")
    return float(token)  # NaN parses natively


def _parse_sample(line: str) -> tuple[str, dict, float]:
    """Parse ``name{label="v",...} value`` → (name, labels, value).
    Raises ValueError on any malformation (bad name, bad escape,
    unterminated quote, missing value)."""
    m = _NAME_RE.match(line)
    if m is None:
        raise ValueError("sample does not start with a metric name")
    name = m.group(0)
    rest = line[m.end():]
    labels: dict[str, str] = {}
    if rest.startswith("{"):
        i = 1
        while True:
            if i >= len(rest):
                raise ValueError("unterminated label set")
            if rest[i] == "}":
                i += 1
                break
            lm = _LABEL_RE.match(rest, i)
            if lm is None:
                raise ValueError(f"bad label name at {rest[i:]!r}")
            key = lm.group(0)
            i = lm.end()
            if i >= len(rest) or rest[i] != "=":
                raise ValueError(f"label {key!r} missing '='")
            i += 1
            if i >= len(rest) or rest[i] != '"':
                raise ValueError(f"label {key!r} value not quoted")
            i += 1
            out = []
            while True:
                if i >= len(rest):
                    raise ValueError(f"label {key!r} unterminated quote")
                ch = rest[i]
                if ch == "\\":
                    if i + 1 >= len(rest) or rest[i + 1] not in '\\"n':
                        raise ValueError(
                            f"label {key!r} has an illegal escape")
                    out.append({"n": "\n"}.get(rest[i + 1], rest[i + 1]))
                    i += 2
                elif ch == '"':
                    i += 1
                    break
                else:
                    out.append(ch)
                    i += 1
            labels[key] = "".join(out)
            if i < len(rest) and rest[i] == ",":
                i += 1
        rest = rest[i:]
    parts = rest.split()
    if not parts:
        raise ValueError("sample has no value")
    return name, labels, _parse_value(parts[0])


def lint(text: str) -> list[str]:
    """Validate one exposition body; returns a list of violations
    (empty = clean)."""
    errors: list[str] = []
    declared: dict[str, str] = {}
    # (family, labelkey) → list of (le, cumulative count), plus the
    # matching _sum/_count samples for cross-checks.
    buckets: dict[tuple, list[tuple[float, float]]] = {}
    counts: dict[tuple, float] = {}
    sums: dict[tuple, float] = {}

    def family_of(name: str) -> str | None:
        if name in declared:
            return name
        for suffix in _SUFFIXES:
            base = name.removesuffix(suffix)
            if (base != name and base in declared
                    and declared[base] in ("histogram", "summary")):
                return base
        return None

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) < 4:
                    errors.append(f"line {lineno}: malformed TYPE line")
                    continue
                name, kind = parts[2], parts[3].strip()
                if _NAME_RE.fullmatch(name) is None:
                    errors.append(
                        f"line {lineno}: bad metric name {name!r}")
                if kind not in _KINDS:
                    errors.append(
                        f"line {lineno}: unknown type {kind!r}")
                if name in declared:
                    errors.append(
                        f"line {lineno}: duplicate TYPE for {name}")
                if kind == "counter" and not name.endswith("_total"):
                    errors.append(
                        f"line {lineno}: counter {name} must end _total")
                declared[name] = kind
            continue
        try:
            name, labels, value = _parse_sample(line)
        except ValueError as e:
            errors.append(f"line {lineno}: {e}")
            continue
        fam = family_of(name)
        if fam is None:
            errors.append(
                f"line {lineno}: sample {name} has no preceding TYPE")
            continue
        kind = declared[fam]
        if kind in ("counter", "gauge") and name != fam:
            errors.append(
                f"line {lineno}: {kind} sample {name} != family {fam}")
        if kind == "counter" and value < 0:
            errors.append(f"line {lineno}: counter {name} is negative")
        if kind == "histogram":
            key = (fam, tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le")))
            if name == f"{fam}_bucket":
                if "le" not in labels:
                    errors.append(
                        f"line {lineno}: {name} sample missing le")
                    continue
                try:
                    le = _parse_value(labels["le"])
                except ValueError:
                    errors.append(
                        f"line {lineno}: unparseable le "
                        f"{labels['le']!r}")
                    continue
                buckets.setdefault(key, []).append((le, value))
            elif name == f"{fam}_count":
                counts[key] = value
            elif name == f"{fam}_sum":
                sums[key] = value

    for (fam, labelkey), series in buckets.items():
        where = f"{fam}{dict(labelkey) if labelkey else ''}"
        les = [le for le, _ in series]
        if les != sorted(les) or len(set(les)) != len(les):
            errors.append(f"{where}: le bounds not strictly increasing")
        cum = [c for _, c in series]
        if any(b < a for a, b in zip(cum, cum[1:])):
            errors.append(f"{where}: bucket counts not cumulative")
        if not les or les[-1] != float("inf"):
            errors.append(f"{where}: missing +Inf bucket")
        elif (fam, labelkey) in counts and cum[-1] != counts[
                (fam, labelkey)]:
            errors.append(f"{where}: +Inf bucket != _count")
        if (fam, labelkey) not in counts:
            errors.append(f"{where}: missing _count")
        if (fam, labelkey) not in sums:
            errors.append(f"{where}: missing _sum")
    return errors


def lint_url(url: str, timeout: float = 10.0) -> list[str]:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        text = resp.read().decode()
    if not text.strip():
        return [f"{url}: empty exposition body"]
    return [f"{url}: {e}" for e in lint(text)]


def _self_check() -> int:
    """Boot every /metrics surface in-process and lint a real scrape of
    each: model server (decoder driven once so histograms/timelines have
    samples), gateway admin, availability prober, operator HealthServer.
    """
    import json
    import socket
    import threading

    from kubeflow_tpu.gateway import Gateway, RouteTable
    from kubeflow_tpu.observability.collector import (
        AvailabilityProber,
        make_server,
    )
    from kubeflow_tpu.operators.base import OPERATOR_METRICS, Controller
    from kubeflow_tpu.runtime import HealthServer
    from kubeflow_tpu.serving.engine import EngineConfig
    from kubeflow_tpu.serving.server import ModelServer

    failures: list[str] = []
    stops = []
    try:
        # 1. Model server — one generation so the decoder's histograms,
        # counters and trace ring all carry real samples.
        server = ModelServer(
            EngineConfig(model="lm-test-tiny", batch_size=2,
                         max_seq_len=32, max_new_tokens=4),
            port=0, batch_timeout_ms=2)
        server.start()
        stops.append(server.stop)
        base = f"http://127.0.0.1:{server.port}"
        req = urllib.request.Request(
            f"{base}/v1/models/lm-test-tiny:predict", method="POST",
            data=json.dumps({"instances": [
                {"tokens": [1, 2, 3], "max_new_tokens": 4}]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            resp.read()
        failures += lint_url(f"{base}/monitoring/prometheus/metrics")

        # 2. Gateway admin port.
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            admin_port = s.getsockname()[1]
        gw = Gateway(RouteTable(), port=0, admin_port=admin_port,
                     probe_interval=0)
        gw.start()
        stops.append(gw.stop)
        failures += lint_url(f"http://127.0.0.1:{admin_port}/metrics")

        # 3. Availability prober, probing the model server's front door.
        prober = AvailabilityProber(f"{base}/healthz", interval=3600)
        prober.probe_once()
        phttpd = make_server(prober, 0)
        threading.Thread(target=phttpd.serve_forever,
                         daemon=True).start()
        stops.append(phttpd.shutdown)
        pport = phttpd.server_address[1]
        failures += lint_url(f"http://127.0.0.1:{pport}/metrics")

        # 4. An operator HealthServer over the shared runtime registry —
        # one reconcile observed so the histogram has samples, plus one
        # REAL scheduling round (fake cluster: nodes + a queued gang) so
        # the scheduler decision families carry samples too.
        class _LintProbe(Controller):
            api_version = "kubeflow-tpu.org/v1"
            kind = "LintProbe"

            def reconcile(self, obj):
                return None

        ctrl = _LintProbe(client=None)
        ctrl._safe_reconcile({"metadata": {"name": "probe"}})
        ctrl._enqueue(("ns", "probe"))

        from kubeflow_tpu.apis import jobs as jobs_api
        from kubeflow_tpu.apis import scheduling as sched_api
        from kubeflow_tpu.k8s import objects as k8s_objects
        from kubeflow_tpu.k8s.fake import FakeApiServer
        from kubeflow_tpu.scheduler.controller import SchedulerController

        fake = FakeApiServer()
        fake.ensure_namespace("kubeflow")
        for crd in jobs_api.all_job_crds():
            fake.apply(crd)
        fake.apply(sched_api.scheduling_policy_crd())
        fake.create(sched_api.scheduling_policy(namespace="kubeflow"))
        fake.create(k8s_objects.node("lint-n0", labels={
            sched_api.NODE_ACCEL_LABEL: "v5e",
            sched_api.NODE_SLICE_LABEL: "v5e-0"}, tpu_chips=4))
        fake.create({
            "apiVersion": jobs_api.JOBS_API_VERSION, "kind": "JaxJob",
            "metadata": {"name": "lint-gang", "namespace": "kubeflow"},
            "spec": {"priority": 1, "replicaSpecs": {"Worker": {
                "replicas": 1, "template": {"spec": {"containers": [
                    {"name": "main", "image": "i"}]}}}}},
        })
        SchedulerController(fake).reconcile_all()
        # One REAL experiment round (synthetic closed-form scenario, two
        # trials) so the experiment/tuning families carry samples, not
        # just TYPE lines.
        from kubeflow_tpu.apis.experiment import (
            experiment as experiment_cr,
            experiment_crd,
        )
        from kubeflow_tpu.operators.experiment import ExperimentController

        fake.apply(experiment_crd())
        fake.create(experiment_cr(
            "lint-exp", "kubeflow", "synthetic-knobs",
            algorithm="random", max_trials=2, parallel_trials=2))
        exp_ctrl = ExperimentController(fake)
        exp_ctrl.reconcile_all()
        exp_ctrl.reconcile_all()
        # The elastic-training reshard families live in the same shared
        # registry (train/elastic.py registers them at import) — pull
        # them in before the scrape so their TYPE lines are asserted.
        import kubeflow_tpu.train.elastic  # noqa: F401

        health = HealthServer(
            0, lambda: {"kubeflow_tpu_controllers_running": 1},
            registry=OPERATOR_METRICS)
        health.start()
        stops.append(health.stop)
        operator_url = f"http://127.0.0.1:{health.port}/metrics"
        failures += lint_url(operator_url)
        # The scheduler decision families (the autoscaler/dashboards'
        # contract) must be present in the operator scrape — a rename
        # or a registry split breaks this, not just an empty gauge.
        from kubeflow_tpu.observability.metrics import type_line

        with urllib.request.urlopen(operator_url, timeout=10) as resp:
            operator_body = resp.read().decode()
        for family, kind in (
                ("scheduler_queue_depth", "gauge"),
                ("scheduler_queue_wait_seconds", "histogram"),
                ("scheduler_placement_seconds", "histogram"),
                ("scheduler_admissions_total", "counter"),
                ("scheduler_preemptions_total", "counter"),
                ("scheduler_requeues_total", "counter"),
                ("scheduler_shrinks_total", "counter"),
                ("scheduler_grows_total", "counter"),
                ("train_reshards_total", "counter"),
                ("train_reshard_seconds", "histogram"),
                ("scheduler_unschedulable_jobs", "gauge"),
                ("experiment_trials_total", "counter"),
                ("experiment_best_objective", "gauge"),
                ("tuning_suggestions_total", "counter")):
            if type_line(family, kind) not in operator_body:
                failures.append(
                    f"{operator_url}: scheduler family {family} missing")
    finally:
        for stop in reversed(stops):
            stop()
    for failure in failures:
        print(f"FAIL {failure}")
    surfaces = "model-server, gateway-admin, prober, operator+scheduler"
    if failures:
        print(f"metrics lint: {len(failures)} violation(s) across "
              f"{surfaces}")
        return 1
    print(f"metrics lint ok ({surfaces})")
    return 0


def main(argv=None) -> int:
    """``python -m kubeflow_tpu.observability.lint [--self-check] [url…]``
    — lint live endpoints by URL, and/or the in-process self-check."""
    argv = list(sys.argv[1:] if argv is None else argv)
    rc = 0
    if "--self-check" in argv:
        argv.remove("--self-check")
        rc = _self_check()
    for url in argv:
        failures = lint_url(url)
        for failure in failures:
            print(f"FAIL {failure}")
        if failures:
            rc = 1
        else:
            print(f"ok {url}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
