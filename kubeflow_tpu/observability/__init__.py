"""Observability: the platform's signal plane.

- :mod:`kubeflow_tpu.observability.metrics` — the unified MetricRegistry
  (Counter/Gauge/Histogram) and the ONE Prometheus exposition renderer
  every ``/metrics`` surface serves through;
- :mod:`kubeflow_tpu.observability.tracing` — ``X-Request-ID``
  propagation, per-stream lifecycle timelines, ``/debug/requests`` and
  chrome-trace export;
- :mod:`kubeflow_tpu.observability.collector` — availability prober
  (metric-collector analogue,
  metric-collector/service-readiness/kubeflow-readiness.py);
- :mod:`kubeflow_tpu.observability.lint` — promtool-style exposition
  checker the CI metrics-lint stage runs against every live endpoint.
"""
