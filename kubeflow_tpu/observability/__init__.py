"""Observability runtimes: availability prober (metric-collector analogue,
metric-collector/service-readiness/kubeflow-readiness.py)."""
