"""Request-scoped tracing: ``X-Request-ID`` propagation + stream timelines.

One request id follows a request across the platform's hops: the gateway
generates it when the client didn't send one, echoes it on the response,
and forwards it upstream; the model server threads it into the continuous
decoder, which records the stream's full lifecycle as a
:class:`Timeline` — submit → queued → admitted → prefill → first token →
per-dispatch emissions → finish/error, including memory-deferral and
prefix-eviction events along the way.

Timelines land in a bounded in-memory :class:`TraceStore` ring served at
``/debug/requests`` (plain JSON, or ``?format=chrome`` for a
chrome://tracing / Perfetto - loadable trace-event file), so a slow
request's breakdown is one curl away. Spans are derived from consecutive
events, which makes the invariant the E2E test pins: the span durations
of a closed timeline sum to exactly its submit→finish wall time.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque

REQUEST_ID_HEADER = "X-Request-ID"


def gen_request_id() -> str:
    """A fresh request id (uuid4, 16 hex chars — log-greppable, collision
    odds irrelevant at ring-buffer lifetimes)."""
    return uuid.uuid4().hex[:16]


class Timeline:
    """Ordered (name, t, attrs) events for one request, t relative to
    creation. Closed timelines are immutable; ``close`` is idempotent and
    always lands the terminal event (the event cap never blocks it), so a
    closed timeline's span sum equals its duration by construction."""

    def __init__(self, request_id: str, *, max_events: int = 96,
                 on_close=None) -> None:
        self.request_id = request_id
        self.start_wall = time.time()
        self.start = time.perf_counter()
        self.status: str | None = None  # None = still open
        self.error: str | None = None
        self._events: list[tuple[str, float, dict]] = []
        self._dropped = 0
        self._max_events = max_events
        self._lock = threading.Lock()
        self._on_close = on_close

    @property
    def open(self) -> bool:
        with self._lock:
            return self.status is None

    def event(self, name: str, **attrs) -> None:
        t = time.perf_counter() - self.start
        with self._lock:
            if self.status is not None:
                return
            if len(self._events) >= self._max_events:
                self._dropped += 1
                return
            self._events.append((name, t, attrs))

    def close(self, status: str = "ok",
              error: BaseException | str | None = None) -> None:
        t = time.perf_counter() - self.start
        with self._lock:
            if self.status is not None:
                return
            attrs = {"error": str(error)} if error is not None else {}
            self._events.append(
                ("error" if error is not None else "finish", t, attrs))
            self.status = "error" if error is not None else status
            self.error = str(error) if error is not None else None
        if self._on_close is not None:
            self._on_close(self)

    def events(self) -> list[tuple[str, float, dict]]:
        with self._lock:
            return list(self._events)

    def spans(self) -> list[dict]:
        """Phase spans between consecutive events: span *i* is named by
        the event that ends it. Their durations tile first→last event, so
        ``sum(durations) == duration_s`` for a closed timeline."""
        events = self.events()
        out = []
        for (_, t0, _a), (name, t1, attrs) in zip(events, events[1:]):
            out.append({"name": name, "start_s": t0,
                        "duration_s": t1 - t0, **attrs})
        return out

    @property
    def duration_s(self) -> float:
        events = self.events()
        if len(events) < 2:
            return 0.0
        return events[-1][1] - events[0][1]

    def to_dict(self) -> dict:
        # One consistent snapshot: /debug/requests renders on an HTTP
        # thread while the decoder closes the timeline — status, error
        # and the drop count must come from the same moment.
        with self._lock:
            status = self.status
            error = self.error
            dropped = self._dropped
        events = self.events()
        return {
            "request_id": self.request_id,
            "start_unix": self.start_wall,
            "status": status or "open",
            "error": error,
            "duration_ms": round(1e3 * self.duration_s, 3),
            "dropped_events": dropped,
            "events": [
                {"name": name, "t_ms": round(1e3 * t, 3), **attrs}
                for name, t, attrs in events
            ],
            "spans": [
                {**s, "start_ms": round(1e3 * s.pop("start_s"), 3),
                 "duration_ms": round(1e3 * s.pop("duration_s"), 3)}
                for s in self.spans()
            ],
        }


class TraceStore:
    """Bounded in-memory timeline store: open timelines indexed live,
    closed ones kept in a fixed-size ring (oldest evicted first) — memory
    is bounded no matter the traffic."""

    def __init__(self, capacity: int = 256, max_events: int = 96) -> None:
        self.capacity = capacity
        self.max_events = max_events
        self._lock = threading.Lock()
        self._live: dict[int, Timeline] = {}
        self._done: deque[Timeline] = deque(maxlen=capacity)

    def start(self, request_id: str | None = None) -> Timeline:
        tl = Timeline(request_id or gen_request_id(),
                      max_events=self.max_events, on_close=self._retire)
        with self._lock:
            self._live[id(tl)] = tl
        return tl

    def _retire(self, tl: Timeline) -> None:
        with self._lock:
            self._live.pop(id(tl), None)
            self._done.append(tl)

    @property
    def open_count(self) -> int:
        with self._lock:
            return len(self._live)

    def open_timelines(self) -> list[Timeline]:
        with self._lock:
            return list(self._live.values())

    def find(self, request_id: str) -> list[dict]:
        with self._lock:
            timelines = list(self._live.values()) + list(self._done)
        return [t.to_dict() for t in timelines
                if t.request_id == request_id]

    def snapshot(self) -> dict:
        with self._lock:
            live = list(self._live.values())
            done = list(self._done)
        return {
            "open": [t.to_dict() for t in live],
            "finished": [t.to_dict() for t in done],
        }

    def chrome_trace(self) -> dict:
        """Trace-event-format export (chrome://tracing, Perfetto): one
        complete ('X') event per span, one track per request."""
        with self._lock:
            timelines = list(self._done) + list(self._live.values())
        events = []
        for tid, tl in enumerate(timelines, start=1):
            base_us = tl.start_wall * 1e6
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"request {tl.request_id}"},
            })
            for span in tl.spans():
                args = {k: v for k, v in span.items()
                        if k not in ("name", "start_s", "duration_s")}
                events.append({
                    "name": span["name"], "ph": "X", "pid": 1, "tid": tid,
                    "ts": base_us + span["start_s"] * 1e6,
                    "dur": span["duration_s"] * 1e6,
                    "args": args,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_debug(store: TraceStore, query: str = "") -> tuple[bytes, str]:
    """Shared ``/debug/requests`` responder: ``(body, content_type)``.
    Plain JSON snapshot by default; ``format=chrome`` in the query string
    selects the trace-event export; ``id=<request_id>`` filters."""
    import json
    from urllib.parse import parse_qs

    params = parse_qs(query)
    if params.get("format", [""])[0] == "chrome":
        payload = store.chrome_trace()
    elif params.get("id", [""])[0]:
        payload = {"requests": store.find(params["id"][0])}
    else:
        payload = store.snapshot()
    return json.dumps(payload, indent=1).encode(), "application/json"
