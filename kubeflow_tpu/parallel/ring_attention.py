"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context support is entirely absent from the reference (SURVEY.md §5.7 —
its only sharding notions are PS sharding and MPI allreduce). Here sequences
are sharded over the ``sequence`` mesh axis; each device holds one query chunk
and streams key/value chunks around the ICI ring with ``ppermute``, folding
each block in with an online-softmax update (flash-attention accumulation).
Communication overlaps compute naturally: XLA schedules the permute for step
i+1 concurrently with the block matmuls for step i.

Memory per device is O(seq/ring × seq/ring) instead of O(seq²); the ring makes
context length scale linearly with the number of devices on the axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel.collectives import (
    axis_size as collectives_axis_size,
    shard_map,
)
from kubeflow_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQUENCE

_NEG_INF = -1e30


def _block_attn(q, k, v, bias, m_prev, num_prev, den_prev, scale):
    """Fold one K/V block into the running online-softmax state.

    q: [B, H, Tq, D]; k,v: [B, H, Tk, D]; bias: additive mask
    broadcastable to [B, H, Tq, Tk] (plain causal use passes [Tq, Tk];
    the serving span ring passes a per-row [B, 1, Tq, Tk]).
    State: running max m [B,H,Tq,1], numerator [B,H,Tq,D], denominator
    [B,H,Tq,1] — all float32 regardless of input dtype.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale + bias
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    # Renormalize previous accumulators to the new max.
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    num = num_prev * correction + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
    )
    den = den_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    return m_new, num, den


def _causal_bias(q_start, k_start, tq, tk):
    q_pos = q_start + jnp.arange(tq)[:, None]
    k_pos = k_start + jnp.arange(tk)[None, :]
    return jnp.where(q_pos >= k_pos, 0.0, _NEG_INF).astype(jnp.float32)


def span_bias(pos, q_start, k_start, tq, tk):
    """Per-row span mask for chunked-prefill ring attention: query token
    ``i`` of row ``b`` sits at global position ``pos[b] + q_start + i``
    and attends keys at global positions ``<= `` its own (its just-written
    K/V included). Returns [B, Tq, Tk] float32 — broadcast to
    ``[B, 1, Tq, Tk]`` before handing it to :func:`_block_attn`."""
    q_pos = pos[:, None, None] + q_start + jnp.arange(tq)[None, :, None]
    k_pos = k_start + jnp.arange(tk)[None, None, :]
    return jnp.where(q_pos >= k_pos, 0.0, _NEG_INF).astype(jnp.float32)


def _ring_attention_sharded(q, k, v, *, causal: bool, axis: str):
    """Per-device body under shard_map. q,k,v: [B, H, T_local, D]."""
    n = collectives_axis_size(axis)
    idx = lax.axis_index(axis)
    b, h, t_local, d = q.shape
    scale = 1.0 / (d**0.5)
    q32 = q.astype(jnp.float32)

    def step(carry, i):
        k_blk, v_blk, m, num, den = carry
        # Block i arrived from device (idx + i) mod n — its global offset.
        src = (idx + i) % n
        if causal:
            bias = _causal_bias(idx * t_local, src * t_local, t_local, t_local)
        else:
            bias = jnp.zeros((t_local, t_local), jnp.float32)
        m, num, den = _block_attn(q32, k_blk, v_blk, bias, m, num, den, scale)
        # Pull the next block from the right neighbor (ring shift by one).
        perm = [(j, (j - 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_blk, axis_name=axis, perm=perm)
        v_nxt = lax.ppermute(v_blk, axis_name=axis, perm=perm)
        return (k_nxt, v_nxt, m, num, den), None

    m0 = jnp.full((b, h, t_local, 1), _NEG_INF, jnp.float32)
    num0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    den0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    (_, _, m, num, den), _ = lax.scan(
        step, (k.astype(jnp.float32), v.astype(jnp.float32), m0, num0, den0),
        jnp.arange(n),
    )
    return (num / den).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    causal: bool = True,
    axis: str = AXIS_SEQUENCE,
    batch_axes=(AXIS_DATA, AXIS_FSDP),
):
    """Exact attention with q/k/v laid out [B@batch_axes, H, T@axis, D].

    Inputs are global arrays (or tracers under jit); output keeps the input
    layout. Batch stays sharded over the data/fsdp axes so each data-parallel
    group runs the ring only on its own examples; pass ``batch_axes=()`` for
    replicated-batch use.
    """
    spec = P(tuple(batch_axes) or None, None, axis, None)
    body = functools.partial(_ring_attention_sharded, causal=causal, axis=axis)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True):
    """Unsharded O(T²) attention — the correctness oracle for tests."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s / (d**0.5)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        s = s + _causal_bias(0, 0, tq, tk)[None, None]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
