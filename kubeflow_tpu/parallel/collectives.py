"""XLA collective wrappers for shard_map code.

Replaces the reference's three transport stacks — TF gRPC parameter servers
(tf-controller-examples/tf-cnn/launcher.py:69-81), OpenMPI ORTE
(kubeflow/mpi-job/mpi-operator.libsonnet:280), and NCCL inside imported GPU
images — with the XLA collectives that ride ICI within a slice and DCN across
slices. These helpers are thin by design: under ``jit`` + sharding constraints
XLA usually inserts collectives itself; explicit calls are for shard_map
regions (ring attention, custom allreduce benchmarks, MoE dispatch).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = False,
              axis_names=None):
    """Version-portable ``jax.shard_map``: newer jax exposes it at the top
    level with ``check_vma``/``axis_names``; older releases spell it
    ``jax.experimental.shard_map.shard_map`` with ``check_rep`` and the
    COMPLEMENT set ``auto`` (axes left automatic rather than axes made
    manual). Every shard_map in this repo goes through here so kernels
    run on both."""
    if hasattr(jax, "shard_map"):
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kwargs)
    from jax.experimental.shard_map import shard_map as _legacy

    kwargs = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    return _legacy(fn, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=check_vma, **kwargs)


def psum(x, axis: str | Sequence[str]):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str | Sequence[str]):
    return lax.pmean(x, axis_name=axis)


def all_gather(x, axis: str, *, dim: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name=axis, axis=dim, tiled=tiled)


def reduce_scatter(x, axis: str, *, dim: int = 0):
    return lax.psum_scatter(x, axis_name=axis, scatter_dimension=dim, tiled=True)


def ring_permute(x, axis: str, *, shift: int = 1):
    """Send x to the neighbor ``shift`` steps around the ring; receive from
    the opposite neighbor. The building block of ring attention and of
    bidirectional-bandwidth allreduce on a torus."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Static mesh-axis size inside a shard_map region, version-portable:
    newer jax has lax.axis_size; older releases constant-fold psum(1)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def shard_map_over(mesh: Mesh, in_specs, out_specs, *, check_vma: bool = False):
    """Decorator: shard_map a function over ``mesh``.

    ``check_vma=False`` by default because collective-heavy kernels routinely
    mix replicated and sharded values.
    """

    def wrap(fn):
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )

    return wrap


def allreduce_mean(mesh: Mesh, axis: str):
    """An explicit-allreduce jitted fn (psum / n, the Horovod convention) —
    the MPIJob benchmark analogue
    (kubeflow/mpi-job/prototypes/mpi-job-custom.jsonnet:35-59), for measuring
    collective bandwidth over ICI rather than for training (training uses
    jit+GSPMD)."""

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    def _allreduce(x):
        return lax.pmean(x, axis_name=axis)

    return _allreduce


def global_norm_sq(tree, axis: str | Sequence[str] | None = None):
    """Sum of squares across a pytree, optionally psummed across ``axis``
    (for use inside shard_map gradient code)."""
    leaves = jax.tree.leaves(tree)
    total = sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves)
    if axis is not None:
        total = lax.psum(total, axis_name=axis)
    return total
