"""TPU-native parallelism library.

The reference platform's parallelism is *replica-typed*: PS/Worker processes
wired by TF_CONFIG (kubeflow/tf-training/tf-job-operator.libsonnet:10-96),
MPI allreduce (kubeflow/mpi-job/mpi-operator.libsonnet:5-28), NCCL inside
imported GPU images. This package replaces all of that with the SPMD model
native to TPUs:

- :mod:`~kubeflow_tpu.parallel.mesh` — device meshes over ICI/DCN with named
  axes for data / fsdp / tensor / sequence / expert parallelism.
- :mod:`~kubeflow_tpu.parallel.sharding` — named-rule pytree sharding (the
  GSPMD analogue of the reference's per-replica resource assignment).
- :mod:`~kubeflow_tpu.parallel.collectives` — XLA collective wrappers
  (psum / all_gather / reduce_scatter / ppermute) for use under shard_map.
- :mod:`~kubeflow_tpu.parallel.distributed` — multi-host rendezvous from the
  operator-injected coordinator env (the TF_CONFIG analogue, SURVEY.md §2.2).
- :mod:`~kubeflow_tpu.parallel.ring_attention` — ring attention over the
  sequence axis for long-context training (absent from the reference,
  SURVEY.md §5.7).
"""

from kubeflow_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_PIPELINE,
    AXIS_FSDP,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
    MeshConfig,
    build_mesh,
)
from kubeflow_tpu.parallel.sharding import (
    PartitionRule,
    batch_spec,
    named_sharding,
    shard_pytree,
    spec_for_path,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_PIPELINE",
    "AXIS_FSDP",
    "AXIS_SEQUENCE",
    "AXIS_TENSOR",
    "MeshConfig",
    "build_mesh",
    "PartitionRule",
    "batch_spec",
    "named_sharding",
    "shard_pytree",
    "spec_for_path",
]
