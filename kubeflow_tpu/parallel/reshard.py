"""Live state resharding between mesh shapes (the Tenplex-style remap).

Rescaling a running job used to mean checkpoint-restore by hand; this
module turns it into a state transformation: given a live pytree (params,
optimizer slots, RNG, step — any leaves) and the target mesh's
``NamedSharding`` tree, :func:`reshard_pytree` moves every leaf onto the
target placement **bit-for-bit**. Two paths:

- **device-to-device** when the source and target device sets overlap
  (the common grow/shrink case — the surviving chips keep their bytes and
  only the delta moves): one ``jax.device_put`` against the target
  shardings, XLA's resharding transfers shard deltas directly;
- **host-gather fallback** when the sets are disjoint (a job migrated to
  a different slice): leaves are fetched to host memory and re-placed,
  which works across any two device sets a single process can see.

Both paths are pure data movement — no arithmetic touches the values, so
the remapped state is bitwise identical to the source (pinned in tests).
The compute that follows it on a different mesh degree is
f32-equivalent-but-not-bitwise to the old degree (psum partial grouping
changes with the shard count — the same caveat class as the serving tp
meshes), which is why the elastic byte-equality contract compares against
the restore-into-target-mesh path, not a fixed-mesh run
(docs/training.md "Elastic training").
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax

from kubeflow_tpu.parallel.mesh import AXIS_DATA, MESH_AXES, MeshConfig


def tree_devices(tree) -> set:
    """The set of devices currently holding any leaf of ``tree``."""
    out: set = set()
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            out |= set(sharding.device_set)
    return out


def shardings_devices(shardings) -> set:
    out: set = set()
    for sh in jax.tree.leaves(
            shardings, is_leaf=lambda x: hasattr(x, "device_set")):
        out |= set(getattr(sh, "device_set", ()))
    return out


@dataclass
class ReshardStats:
    """What one remap did (the Timeline-style record the train result
    carries)."""

    from_devices: int = 0
    to_devices: int = 0
    method: str = "device"  # "device" | "host"
    leaves: int = 0
    bytes: int = 0
    seconds: float = 0.0

    @property
    def direction(self) -> str:
        return "grow" if self.to_devices >= self.from_devices else "shrink"

    def to_dict(self) -> dict:
        return {
            "from_devices": self.from_devices,
            "to_devices": self.to_devices,
            "direction": self.direction,
            "method": self.method,
            "leaves": self.leaves,
            "bytes": self.bytes,
            "seconds": round(self.seconds, 6),
        }


@dataclass
class ReshardResult:
    tree: object
    stats: ReshardStats = field(default_factory=ReshardStats)


def reshard_pytree(tree, shardings) -> ReshardResult:
    """Remap ``tree`` onto ``shardings`` (a matching pytree of
    ``NamedSharding``), bit-for-bit. Chooses device-to-device transfer
    when the device sets overlap, host-gather otherwise. Blocks until
    the remapped leaves are resident, so the caller's timing (and the
    source buffers' release) is real, not dispatch latency."""
    import time

    src = tree_devices(tree)
    dst = shardings_devices(shardings)
    stats = ReshardStats(
        from_devices=len(src), to_devices=len(dst),
        leaves=len(jax.tree.leaves(tree)),
        bytes=sum(getattr(x, "nbytes", 0) for x in jax.tree.leaves(tree)),
    )
    t0 = time.perf_counter()
    if src and dst and not (src & dst):
        # Disjoint sets: XLA cannot be assumed to route between device
        # sets that share no member (cross-slice moves) — stage through
        # host memory, then place with the target shardings.
        stats.method = "host"
        host = jax.device_get(tree)
        out = jax.device_put(host, shardings)
    else:
        stats.method = "device"
        out = jax.device_put(tree, shardings)
    jax.block_until_ready(jax.tree.leaves(out))
    stats.seconds = time.perf_counter() - t0
    return ReshardResult(tree=out, stats=stats)


def scaled_mesh_config(base: MeshConfig, n_devices: int) -> MeshConfig:
    """The target mesh shape for an elastic resize: the **data** axis
    absorbs the change (the only axis whose degree is free of the model's
    geometry — fsdp/tensor/… splits are dimension-bound), every other
    axis keeps its degree. Raises when ``n_devices`` is not divisible by
    the fixed axes' product (the scheduler grants whole multiples of the
    per-host chip count, so a clean spec never hits this)."""
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    degrees = base.degrees()
    fixed = math.prod(d for a, d in degrees.items()
                      if a != AXIS_DATA and d != -1)
    if any(d == -1 for a, d in degrees.items() if a != AXIS_DATA):
        raise ValueError(
            "elastic resize needs every non-data axis degree explicit; "
            f"got {degrees}")
    if n_devices % fixed:
        raise ValueError(
            f"{n_devices} devices not divisible by the fixed axes' "
            f"product {fixed} — cannot scale the data axis")
    kwargs = {a: degrees[a] for a in MESH_AXES}
    kwargs[AXIS_DATA] = n_devices // fixed
    return MeshConfig(**kwargs, ici_axes=base.ici_axes)
