"""Device-mesh construction with named parallelism axes.

The reference expresses scale as replica counts on a CRD
(e.g. numPs/numWorkers, kubeflow/tf-training/tf-job-operator.libsonnet:10-96;
numGpus, kubeflow/pytorch-job/prototypes/pytorch-job.jsonnet:26-32). The TPU
equivalent is a :class:`jax.sharding.Mesh` whose named axes carry the
parallelism strategy; XLA inserts the collectives. Axis order here is chosen
so the highest-bandwidth-demand axes (tensor, then sequence) land on the
innermost ICI dimensions, while pure-data axes tolerate DCN.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names, outermost (DCN-tolerant) to innermost (ICI-hungry).
# Pipeline sits next to data: stage boundaries move one activation per
# microbatch step (point-to-point), the lowest-bandwidth collective here.
AXIS_DATA = "data"
AXIS_PIPELINE = "pipeline"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_SEQUENCE = "sequence"
AXIS_TENSOR = "tensor"

MESH_AXES: tuple[str, ...] = (
    AXIS_DATA,
    AXIS_PIPELINE,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
)


@dataclass(frozen=True)
class MeshConfig:
    """Degrees of each parallelism axis.

    Any axis may be -1 (at most one), meaning "absorb the remaining devices" —
    the same convenience the reference exposes by letting replica counts
    default from cluster size.
    """

    data: int = -1
    pipeline: int = 1
    fsdp: int = 1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1
    # Axes that collectively must map onto a single slice's ICI. Used by the
    # operator's topology allocator; informational on a single host.
    ici_axes: tuple[str, ...] = field(
        default=(AXIS_EXPERT, AXIS_SEQUENCE, AXIS_TENSOR), repr=False
    )

    def degrees(self) -> dict[str, int]:
        return {
            AXIS_DATA: self.data,
            AXIS_PIPELINE: self.pipeline,
            AXIS_FSDP: self.fsdp,
            AXIS_EXPERT: self.expert,
            AXIS_SEQUENCE: self.sequence,
            AXIS_TENSOR: self.tensor,
        }

    def resolve(self, n_devices: int) -> dict[str, int]:
        """Fill in the one -1 axis and validate the product equals n_devices."""
        degrees = self.degrees()
        wildcard = [name for name, d in degrees.items() if d == -1]
        if len(wildcard) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wildcard}")
        fixed = math.prod(d for d in degrees.values() if d != -1)
        if wildcard:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            degrees[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {degrees} needs {fixed} devices but {n_devices} are present"
            )
        return degrees


def hybrid_shapes(degrees: dict[str, int], num_slices: int
                  ) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """Split resolved axis degrees into (per-slice ICI shape, DCN shape).

    Multislice deployments put the slice dimension on the `data` axis
    (gradient allreduce tolerates DCN latency; tensor/sequence/expert
    traffic must stay on ICI — SURVEY §2.2's multislice mandate). The data
    degree must be a multiple of num_slices."""
    if degrees[AXIS_DATA] % num_slices:
        raise ValueError(
            f"data degree {degrees[AXIS_DATA]} not divisible by "
            f"num_slices {num_slices}; multislice scales the data axis"
        )
    ici = tuple(
        degrees[a] // num_slices if a == AXIS_DATA else degrees[a]
        for a in MESH_AXES
    )
    dcn = tuple(num_slices if a == AXIS_DATA else 1 for a in MESH_AXES)
    return ici, dcn


def arrange_devices(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    num_slices: int | None = None,
) -> np.ndarray:
    """Place devices into the canonical [data, pipeline, fsdp, expert,
    sequence, tensor] array (the Mesh body, separated from Mesh
    construction so placement is unit-testable with fabricated devices).

    On TPU, placement delegates to ``mesh_utils.create_device_mesh`` so
    axes map contiguously onto the physical torus; on a multislice
    deployment (devices report distinct ``slice_index``es — the MEGASCALE
    path the operator configures) the hybrid builder keeps ICI-hungry
    axes within slices and spans slices on the data axis over DCN. On
    CPU/virtual devices the flat device list is reshaped (placement is
    meaningless there), but ``num_slices`` still applies the hybrid
    data-axis split with slice-major device grouping — the emulation the
    multichip dryrun and the fake-slice E2E run so the DCN-mapped mesh
    path executes without multislice hardware.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    degrees = config.resolve(len(devices))
    shape = tuple(degrees[a] for a in MESH_AXES)
    if num_slices is not None and num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        n_slices = len(slice_ids) if num_slices is None else num_slices
        if len(slice_ids) != n_slices:
            # An explicit degree must match what the hardware reports —
            # a mismatch would either feed create_hybrid_device_mesh an
            # impossible DCN shape or (num_slices=1 on a multislice gang)
            # silently map ICI-hungry axes across the DCN boundary.
            raise ValueError(
                f"num_slices={n_slices} but the TPU devices report "
                f"{len(slice_ids)} distinct slice_index value(s)"
            )
        if n_slices > 1:
            ici, dcn = hybrid_shapes(degrees, n_slices)
            return mesh_utils.create_hybrid_device_mesh(
                ici, dcn, devices=np.asarray(devices)
            )
        return mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices)
        )
    if num_slices and num_slices > 1:
        # Emulated multislice: hybrid_shapes validates the DCN split; the
        # slice-major layout then comes for free from the plain reshape —
        # data is the leading mesh axis, so contiguous per-slice device
        # groups land on contiguous data-axis rows (the same logical
        # layout create_hybrid_device_mesh produces).
        hybrid_shapes(degrees, num_slices)
    return np.asarray(devices).reshape(shape)


def build_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    num_slices: int | None = None,
) -> Mesh:
    """Build a Mesh with the canonical axis names (see
    :func:`arrange_devices` for placement semantics)."""
    return Mesh(
        arrange_devices(config, devices=devices, num_slices=num_slices),
        MESH_AXES,
    )


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """A 1×1×1×1×1 mesh — lets the same pjit code path run on one chip."""
    device = device or jax.devices()[0]
    return build_mesh(MeshConfig(data=1), devices=[device])


def serving_mesh(tp: int, *, cp: int = 1, pp: int = 1,
                 devices: Sequence[jax.Device] | None = None) -> Mesh:
    """The model-parallel serving layout: a ``pp×cp×tp`` mesh over the
    first ``pp*cp*tp`` devices (one replica == one such mesh; every
    other axis is 1, so the tensor split lands on the innermost ICI
    dimension, the context ring just outside it, and the pipeline axis
    outermost). ``cp`` sizes the ``sequence`` axis that chunked-prefill
    ring attention shards long prompts over; ``pp`` sizes the
    ``pipeline`` axis that the layer-stacked weights and the KV pool's
    leading layer dim shard over. Serving replicates nothing across
    data/fsdp: the fleet layer scales replicas, the mesh scales the
    model."""
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if cp < 1:
        raise ValueError(f"cp must be >= 1, got {cp}")
    if pp < 1:
        raise ValueError(f"pp must be >= 1, got {pp}")
    need = tp * cp * pp
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < need:
        raise ValueError(
            f"tp={tp} cp={cp} pp={pp} needs {need} devices but only "
            f"{len(devices)} are visible")
    return build_mesh(MeshConfig(data=1, pipeline=pp, sequence=cp,
                                 tensor=tp),
                      devices=devices[:need])
