"""Multi-host rendezvous from operator-injected environment.

The reference's operators wire workers together by injecting ``TF_CONFIG``
(cluster host lists + task index — consumed at
tf-controller-examples/tf-cnn/launcher.py:69-81) or MPI hostfiles delivered by
kubectl-delivery (kubeflow/mpi-job/mpi-operator.libsonnet:280). Our JaxJob
controller injects three env vars instead (kubeflow_tpu/apis/jobs.py) and every
worker calls :func:`initialize_from_env`, which performs the
``jax.distributed.initialize`` rendezvous — the single entry point for both
ICI (intra-slice) and DCN (multi-slice) topologies.
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass

import jax

from kubeflow_tpu.apis.jobs import (
    ENV_COORDINATOR_ADDRESS,
    ENV_NUM_PROCESSES,
    ENV_NUM_SLICES,
    ENV_PROCESS_ID,
    ENV_SLICE_ID,
)

ENV_MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"


@dataclass(frozen=True)
class ProcessInfo:
    coordinator_address: str | None
    num_processes: int
    process_id: int
    # Multislice (MEGASCALE) topology, injected by the JaxJob controller
    # when spec.tpu.numSlices > 1 (operators/jobs.py): libtpu's DCN
    # transport reads MEGASCALE_COORDINATOR_ADDRESS; the mesh layer reads
    # num_slices to put the slice dimension on the data axis
    # (parallel/mesh.py hybrid placement).
    num_slices: int = 1
    slice_id: int = 0
    megascale_coordinator: str | None = None

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1


def process_info_from_env(environ=None) -> ProcessInfo:
    env = os.environ if environ is None else environ
    return ProcessInfo(
        coordinator_address=env.get(ENV_COORDINATOR_ADDRESS),
        num_processes=int(env.get(ENV_NUM_PROCESSES, "1")),
        process_id=int(env.get(ENV_PROCESS_ID, "0")),
        num_slices=int(env.get(ENV_NUM_SLICES, "1")),
        slice_id=int(env.get(ENV_SLICE_ID, "0")),
        megascale_coordinator=env.get(ENV_MEGASCALE_COORDINATOR),
    )


def initialize_from_env(environ=None) -> ProcessInfo:
    """Join the job's collective. No-op for single-process jobs, so the same
    worker image runs unmodified on one chip or a multi-host slice (the
    property the reference gets from launcher.py tolerating absent TF_CONFIG).

    On a multislice gang the controller also injects the MEGASCALE vars;
    libtpu reads them from the process environment at backend init, so
    when the caller passed an explicit ``environ`` they are exported
    before ``jax.distributed.initialize`` creates the TPU client.
    """
    info = process_info_from_env(environ)
    if info.is_multislice:
        if not info.megascale_coordinator:
            raise RuntimeError(
                f"{ENV_NUM_SLICES}>1 but {ENV_MEGASCALE_COORDINATOR} is "
                "unset; the JaxJob controller must inject the DCN "
                "coordinator address"
            )
        # libtpu reads these from os.environ, not from any argument —
        # assign unconditionally so a stale inherited value can't make
        # libtpu and the mesh layer disagree on the DCN topology.
        os.environ[ENV_MEGASCALE_COORDINATOR] = info.megascale_coordinator
        os.environ[ENV_NUM_SLICES] = str(info.num_slices)
        os.environ[ENV_SLICE_ID] = str(info.slice_id)
    if info.is_distributed:
        if not info.coordinator_address:
            raise RuntimeError(
                f"{ENV_NUM_PROCESSES}>1 but {ENV_COORDINATOR_ADDRESS} is unset; "
                "the JaxJob controller must inject the coordinator service address"
            )
        jax.distributed.initialize(
            coordinator_address=info.coordinator_address,
            num_processes=info.num_processes,
            process_id=info.process_id,
        )
    return info


_gang_seq = itertools.count()


def global_any(flag: bool, *, timeout_ms: int = 60_000) -> bool:
    """All-reduce a per-process boolean over the gang's coordination
    service: True everywhere iff ANY process passed True. The per-step
    agreement that makes graceful preemption collective-safe — kubelet
    evictions deliver SIGTERM per pod at different steps, but orbax's
    save is a barrier across the gang, so every process must break (and
    checkpoint) at the SAME step.

    Rides the jax.distributed KV store + barrier rather than a device
    collective: no XLA dispatch enters the step pipeline, and it works
    on every backend (the CPU fake gang included). Every process must
    call this at the same loop point and the same number of times — the
    call counter doubles as the agreement round id. Single-process is a
    local no-op."""
    if jax.process_count() <= 1:
        return bool(flag)
    from jax._src import distributed as _distributed

    client = _distributed.global_state.client
    seq = next(_gang_seq)
    prefix = f"ktpu/stop/{seq}/"
    client.key_value_set(f"{prefix}{jax.process_index()}",
                         "1" if flag else "0")
    # Without the barrier a fast process could read before a slow one
    # writes and the gang would disagree; with it, the timeout (not a
    # deadlock) is the failure mode when a peer died uncleanly.
    client.wait_at_barrier(f"ktpu/stop-barrier/{seq}", timeout_ms)
    votes = client.key_value_dir_get(prefix)
    if seq > 0:
        try:  # best-effort GC of the previous round's keys
            client.key_value_delete(f"ktpu/stop/{seq - 1}/")
        except Exception:
            pass
    return any(vote == "1" for _, vote in votes)


_min_seq = itertools.count()


def global_min_int(value: int, *, timeout_ms: int = 60_000) -> int:
    """All-reduce an integer over the gang's coordination service,
    returning the MINIMUM everywhere. The elastic reshard agreement
    (train/elastic.py): each process reports the resize target it has
    observed (or a +inf sentinel), and the gang acts on the reduced
    value — identical on every process, so a placement rewrite that
    lands between different steps on different processes still produces
    one common reshard step (the earliest observer's value wins for the
    whole gang). Same KV+barrier transport as :func:`global_any`: every
    process must call this at the same loop point and the same number
    of times. Single-process returns the local value."""
    if jax.process_count() <= 1:
        return int(value)
    from jax._src import distributed as _distributed

    client = _distributed.global_state.client
    seq = next(_min_seq)
    prefix = f"ktpu/min/{seq}/"
    client.key_value_set(f"{prefix}{jax.process_index()}", str(int(value)))
    client.wait_at_barrier(f"ktpu/min-barrier/{seq}", timeout_ms)
    votes = client.key_value_dir_get(prefix)
    if seq > 0:
        try:  # best-effort GC of the previous round's keys
            client.key_value_delete(f"ktpu/min/{seq - 1}/")
        except Exception:
            pass
    return min(int(v) for _, v in votes)


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (checkpoint/teardown
    ordering — the role the openmpi sidecar's file signals play at
    components/openmpi-controller/controller/controller.py:17-116)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def shutdown() -> None:
    # jax.distributed.is_initialized is missing on some jax versions;
    # the client handle is the portable initialized-ness signal.
    from jax._src import distributed as _distributed

    if _distributed.global_state.client is not None:
        jax.distributed.shutdown()
