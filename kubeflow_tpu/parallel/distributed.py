"""Multi-host rendezvous from operator-injected environment.

The reference's operators wire workers together by injecting ``TF_CONFIG``
(cluster host lists + task index — consumed at
tf-controller-examples/tf-cnn/launcher.py:69-81) or MPI hostfiles delivered by
kubectl-delivery (kubeflow/mpi-job/mpi-operator.libsonnet:280). Our JaxJob
controller injects three env vars instead (kubeflow_tpu/apis/jobs.py) and every
worker calls :func:`initialize_from_env`, which performs the
``jax.distributed.initialize`` rendezvous — the single entry point for both
ICI (intra-slice) and DCN (multi-slice) topologies.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax

from kubeflow_tpu.apis.jobs import (
    ENV_COORDINATOR_ADDRESS,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
)


@dataclass(frozen=True)
class ProcessInfo:
    coordinator_address: str | None
    num_processes: int
    process_id: int

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def process_info_from_env(environ=None) -> ProcessInfo:
    env = os.environ if environ is None else environ
    return ProcessInfo(
        coordinator_address=env.get(ENV_COORDINATOR_ADDRESS),
        num_processes=int(env.get(ENV_NUM_PROCESSES, "1")),
        process_id=int(env.get(ENV_PROCESS_ID, "0")),
    )


def initialize_from_env(environ=None) -> ProcessInfo:
    """Join the job's collective. No-op for single-process jobs, so the same
    worker image runs unmodified on one chip or a multi-host slice (the
    property the reference gets from launcher.py tolerating absent TF_CONFIG).
    """
    info = process_info_from_env(environ)
    if info.is_distributed:
        if not info.coordinator_address:
            raise RuntimeError(
                f"{ENV_NUM_PROCESSES}>1 but {ENV_COORDINATOR_ADDRESS} is unset; "
                "the JaxJob controller must inject the coordinator service address"
            )
        jax.distributed.initialize(
            coordinator_address=info.coordinator_address,
            num_processes=info.num_processes,
            process_id=info.process_id,
        )
    return info


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (checkpoint/teardown
    ordering — the role the openmpi sidecar's file signals play at
    components/openmpi-controller/controller/controller.py:17-116)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def shutdown() -> None:
    if jax.distributed.is_initialized():
        jax.distributed.shutdown()
