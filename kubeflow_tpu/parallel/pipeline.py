"""Pipeline parallelism: GPipe microbatch schedule over the `pipeline`
mesh axis.

Layer stacks already carry a leading L dim (the lax.scan representation),
so pipeline stages are just that dim sharded over the `pipeline` axis —
each device holds L/S contiguous layers. The schedule runs inside
``shard_map``: at step t, stage s processes microbatch t−s (bubble steps
compute on garbage and discard — branchless, so the loop body stays one
fused program), and activations move stage→stage+1 with a single
``lax.ppermute`` per step. Total steps = n_micro + S − 1; efficiency
n_micro / (n_micro + S − 1), the GPipe bubble.

Backward is plain autodiff: ppermute transposes to the reverse permute, so
the cotangents flow backward through the pipeline in the same schedule —
no hand-written backward pass.

The reference has no analogue (its parallelism is PS/allreduce replica
counts, SURVEY §2.2); this is part of the §5.7 mandate alongside
tensor/sequence/expert parallelism.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel.collectives import shard_map as _shard_map
from kubeflow_tpu.parallel.mesh import AXIS_PIPELINE


def stage_layer_ranges(n_layers: int, n_stages: int
                       ) -> list[tuple[int, int]]:
    """The contiguous ``[start, stop)`` layer range each pipeline stage
    owns when the stacked L dim shards over the ``pipeline`` axis — the
    single source of truth the serving layer uses to size per-stage KV
    (stage ``s`` holds exactly its range's slice of the pool, so
    per-chip KV bytes divide by ``n_stages``) and to validate the
    ``pp_stages`` knob."""
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_layers % n_stages:
        raise ValueError(
            f"n_layers {n_layers} not divisible by pp_stages {n_stages}")
    per = n_layers // n_stages
    return [(s * per, (s + 1) * per) for s in range(n_stages)]


def pipeline_apply(layer_fn, stage_params, x, mesh, *, n_micro: int):
    """Run ``x`` through the full layer stack with GPipe scheduling.

    ``layer_fn(layer_params, x) -> x`` applies ONE layer (weights without
    the leading L dim). ``stage_params`` is the stacked [L, ...] pytree;
    the L dim is split over the `pipeline` axis. ``x`` [B, T, D] keeps
    whatever data/fsdp sharding it arrived with (those axes stay auto);
    B must divide by n_micro. Returns [B, T, D], same sharding.
    """
    n_stages = mesh.shape[AXIS_PIPELINE]
    if n_stages == 1:
        # Degenerate: plain scan, no schedule.
        def body(h, layer):
            return layer_fn(layer, h), None

        return lax.scan(body, x, stage_params)[0]

    @functools.partial(
        _shard_map,
        mesh=mesh,
        # Only `pipeline` is manual; data/fsdp/tensor/... stay auto, so the
        # schedule composes with the other parallelism axes — GSPMD keeps
        # sharding the per-stage compute from the outer annotations.
        axis_names=frozenset({AXIS_PIPELINE}),
        in_specs=(P(AXIS_PIPELINE), P()),
        out_specs=P(),
        check_vma=False,
    )
    def run(local_params, xb32):
        # f32 at the shard_map boundary: the transpose of a replicated-in
        # input is a psum over `pipeline`, and XLA CPU's AllReducePromotion
        # crashes on bf16 all-reduces; compute stays in the caller's dtype.
        xb = xb32.astype(x.dtype)
        stage = lax.axis_index(AXIS_PIPELINE)
        b = xb.shape[0]
        if b % n_micro:
            raise ValueError(
                f"per-shard batch {b} not divisible by n_micro {n_micro}"
            )
        micro = xb.reshape(n_micro, b // n_micro, *xb.shape[1:])

        def local_stack(h):
            def body(h, layer):
                return layer_fn(layer, h), None

            return lax.scan(body, h, local_params)[0]

        def step(carry, t):
            state, out_buf = carry
            # Stage 0 ingests microbatch t (clamped: bubble steps recompute
            # an already-consumed microbatch and the result is discarded).
            feed = micro[jnp.clip(t, 0, n_micro - 1)]
            x_in = jnp.where(stage == 0, feed, state)
            y = local_stack(x_in)
            # Last stage owns microbatch t-(S-1)'s final activations.
            idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            take = (stage == n_stages - 1) & (t >= n_stages - 1)
            out_buf = out_buf.at[idx].set(
                jnp.where(take, y, out_buf[idx])
            )
            state = lax.ppermute(
                y, AXIS_PIPELINE,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            return (state, out_buf), None

        steps = n_micro + n_stages - 1
        init = (jnp.zeros_like(micro[0]), jnp.zeros_like(micro))
        (_, out_buf), _ = lax.scan(step, init, jnp.arange(steps))
        # Only the last stage holds real outputs; the psum of masked
        # buffers replicates them across the pipeline axis so out_specs
        # (replicated over `pipeline`) is truthful. The reduce runs in f32:
        # XLA CPU's AllReducePromotion pass crashes cloning a bf16
        # all-reduce (observed: "Invalid binary instruction opcode copy").
        masked = jnp.where(
            stage == n_stages - 1, out_buf, 0.0
        ).astype(jnp.float32)
        out = lax.psum(masked, AXIS_PIPELINE)
        return out.reshape(xb.shape)

    return run(stage_params, x.astype(jnp.float32)).astype(x.dtype)
