"""Named-rule pytree sharding.

The reference assigns compute by labeling replicas (PS vs Worker) and letting
the runtime place tensors; the GSPMD analogue is a table of rules mapping
parameter paths to :class:`~jax.sharding.PartitionSpec`s. Rules are matched by
regex over the ``/``-joined pytree path, first match wins — the same
precedence model as the reference's componentParams overrides
(bootstrap/config/kfctl_default.yaml:5-40).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQUENCE


@dataclass(frozen=True)
class PartitionRule:
    """Map parameter paths matching ``pattern`` to ``spec``."""

    pattern: str
    spec: P

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


def path_str(key_path) -> str:
    """Render a jax key path as 'a/b/0/c' for rule matching."""
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def spec_for_path(path: str, rules: list[PartitionRule], default: P = P()) -> P:
    for rule in rules:
        if rule.matches(path):
            return rule.spec
    return default


def tree_specs(tree, rules: list[PartitionRule], default: P = P()):
    """PartitionSpec pytree matching ``tree``'s structure. A rule whose spec
    names more dims than the leaf has falls back to ``default`` — optimizer
    slots with factored/reduced shapes (adafactor's v_row/v_col vectors)
    live under the same paths as the params their rules target."""

    def spec_of(kp, leaf):
        spec = spec_for_path(path_str(kp), rules, default)
        ndim = getattr(leaf, "ndim", None)
        if ndim is not None and len(spec) > ndim:
            return default
        return spec

    return jax.tree_util.tree_map_with_path(spec_of, tree)


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def tree_shardings(mesh: Mesh, tree, rules: list[PartitionRule], default: P = P()):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        tree_specs(tree, rules, default),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_pytree(tree, mesh: Mesh, rules: list[PartitionRule], default: P = P()):
    """Place every leaf of ``tree`` per the first matching rule."""
    return jax.device_put(tree, tree_shardings(mesh, tree, rules, default))


def batch_spec(sequence_sharded: bool = False) -> P:
    """Spec for [batch, seq, ...] activations: batch over data×fsdp, and the
    sequence dim over the sequence axis when context parallelism is on."""
    if sequence_sharded:
        return P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE)
    return P((AXIS_DATA, AXIS_FSDP))


def constrain(x, mesh: Mesh, spec: P):
    """with_sharding_constraint pinned to a mesh (safe outside jit too)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
