"""GCP provisioning driver: project init, cluster + TPU node-pool
create/update with blocking waits, IAM bindings, and platform secrets.

The depth the reference's gcp KfApp has (bootstrap/pkg/kfapp/gcp/gcp.go):
``gcpInitProject`` enables the service APIs (:1170-1199), ``updateDM``
creates/updates infrastructure and ``blockingWait`` polls the operation
until done (:480, :221-252), ``Apply`` then binds IAM roles and bootstraps
k8s (namespace + admin binding, :567-651, :317-358) and ``createSecrets``
materializes credentials as k8s Secrets (:1078-1168). Deployment Manager is
replaced by direct gcloud container/TPU surface — the current-generation
path for TPU node pools.

All gcloud interaction goes through :class:`GcloudRunner`, which in dry-run
mode records the exact commands and returns scripted outputs — the tests'
seam, and also `kfctl generate && kfctl apply --dry-run`'s preview.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import time
from dataclasses import dataclass, field

import yaml

logger = logging.getLogger(__name__)

# Service APIs the platform needs enabled (gcpInitProject's enabledApis
# list, gcp.go:1170-1199, with TPU replacing ML Engine).
REQUIRED_SERVICES = (
    "container.googleapis.com",
    "tpu.googleapis.com",
    "compute.googleapis.com",
    "iam.googleapis.com",
    "logging.googleapis.com",
    "monitoring.googleapis.com",
)

OPERATION_POLL_SECONDS = 10.0
OPERATION_TIMEOUT_SECONDS = 1800.0


class GcloudError(RuntimeError):
    pass


@dataclass
class GcloudRunner:
    """Runs gcloud commands; dry_run records them and plays back scripted
    stdout (FIFO per command prefix, then '{}')."""

    dry_run: bool = False
    history: list[list[str]] = field(default_factory=list)
    scripted: dict[str, list[str]] = field(default_factory=dict)
    sleep = staticmethod(time.sleep)

    def run(self, *args: str) -> str:
        argv = ["gcloud", *args]
        self.history.append(argv)
        if self.dry_run:
            for prefix, outputs in self.scripted.items():
                if " ".join(argv).startswith(prefix) and outputs:
                    return outputs.pop(0)
            return "{}"
        if shutil.which("gcloud") is None:
            raise GcloudError(
                "gcloud is not installed; re-run with --dry-run to preview "
                "the provisioning commands"
            )
        proc = subprocess.run(argv, capture_output=True, text=True)
        if proc.returncode != 0:
            raise GcloudError(
                f"{' '.join(argv)} failed: {proc.stderr.strip()[:500]}"
            )
        return proc.stdout


class GcpProvisioner:
    """The gcp.go Apply flow against the configs generate() wrote."""

    def __init__(self, runner: GcloudRunner):
        self.runner = runner

    # -- project ------------------------------------------------------

    def init_project(self, project: str) -> None:
        """Enable required service APIs (gcpInitProject, gcp.go:1170)."""
        out = self.runner.run(
            "services", "list", "--enabled", f"--project={project}",
            "--format=json",
        )
        enabled = {s.get("config", {}).get("name", s.get("name", ""))
                   for s in _json(out, [])}
        for svc in REQUIRED_SERVICES:
            if svc not in enabled:
                self.runner.run(
                    "services", "enable", svc, f"--project={project}"
                )

    # -- cluster + TPU pool --------------------------------------------

    def ensure_cluster(self, cluster: dict) -> None:
        """Create the cluster and its node pools if absent; block on the
        returned operations (updateDM + blockingWait, gcp.go:480/:221)."""
        project, zone = cluster["project"], cluster["zone"]
        name = cluster["name"]
        existing = _json(self.runner.run(
            "container", "clusters", "list", f"--project={project}",
            f"--zone={zone}", "--format=json",
        ), [])
        if name not in [c.get("name") for c in existing]:
            pool = cluster["nodePools"][0]
            self.runner.run(
                "container", "clusters", "create", name,
                f"--project={project}", f"--zone={zone}",
                f"--machine-type={pool['machineType']}",
                f"--num-nodes={pool['initialNodeCount']}",
                "--async", "--format=json",
            )
            self.block_on_operations(project, zone)
        live_pools = _json(self.runner.run(
            "container", "node-pools", "list", f"--cluster={name}",
            f"--project={project}", f"--zone={zone}", "--format=json",
        ), [])
        live_names = [p.get("name") for p in live_pools]
        for pool in cluster["nodePools"][1:]:
            if pool["name"] in live_names:
                continue
            args = [
                "container", "node-pools", "create", pool["name"],
                f"--cluster={name}", f"--project={project}",
                f"--zone={zone}", f"--machine-type={pool['machineType']}",
                f"--num-nodes={pool['initialNodeCount']}",
            ]
            topo = pool.get("placementPolicy", {}).get("tpuTopology")
            if topo:
                args.append(f"--tpu-topology={topo}")
            if pool.get("autoscaling", {}).get("enabled"):
                args += [
                    "--enable-autoscaling",
                    f"--min-nodes={pool['autoscaling']['minNodeCount']}",
                    f"--max-nodes={pool['autoscaling']['maxNodeCount']}",
                ]
            self.runner.run(*args, "--async", "--format=json")
            self.block_on_operations(project, zone)

    def block_on_operations(self, project: str, zone: str,
                            timeout: float = OPERATION_TIMEOUT_SECONDS
                            ) -> None:
        """Poll container operations until none are running — the
        blockingWait loop (gcp.go:221-252), with its deadline."""
        deadline = time.monotonic() + timeout
        while True:
            ops = _json(self.runner.run(
                "container", "operations", "list", f"--project={project}",
                f"--zone={zone}", "--format=json",
            ), [])
            pending = [op for op in ops
                       if op.get("status") not in ("DONE", "ABORTING")]
            errors = [op for op in ops
                      if op.get("status") == "DONE" and op.get("error")]
            if errors:
                raise GcloudError(f"operation failed: {errors[0]}")
            if not pending:
                return
            if time.monotonic() > deadline:
                raise GcloudError(
                    f"timed out waiting on operations: "
                    f"{[op.get('name') for op in pending]}"
                )
            self.runner.sleep(OPERATION_POLL_SECONDS)

    # -- IAM ------------------------------------------------------------

    def apply_iam_bindings(self, project: str, bindings: list[dict]) -> None:
        """Additive role bindings (the iam_bindings.yaml generate() wrote;
        createIamBindings semantics, gcp.go:567-651)."""
        for binding in bindings:
            for member in binding.get("members", []):
                self.runner.run(
                    "projects", "add-iam-policy-binding", project,
                    f"--member={member}", f"--role={binding['role']}",
                    "--format=json",
                )

    # -- k8s bootstrap + secrets -----------------------------------------

    def bootstrap_k8s(self, client, kfdef) -> None:
        """Namespace + admin binding + platform secrets on the deployment
        cluster (ConfigK8s/bindAdmin gcp.go:317-358, createSecrets :1078)."""
        from kubeflow_tpu.k8s import objects as k8s

        ns = kfdef.spec.namespace
        client.apply(k8s.namespace_obj(ns))
        client.apply(k8s.cluster_role_binding(
            f"{kfdef.name}-admin", "cluster-admin",
            f"{kfdef.name}-admin", ns,
        ))
        email = (f"{kfdef.name}-admin@{kfdef.spec.project}"
                 ".iam.gserviceaccount.com")
        key_json = self._service_account_key(email)
        client.apply({
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {"name": "admin-gcp-sa", "namespace": ns},
            "type": "Opaque",
            "stringData": {"admin-gcp-sa.json": key_json},
        })

    def _service_account_key(self, email: str) -> str:
        """Mint a key for the admin SA (createGcpSecret, gcp.go:1078-1120).
        In dry-run the scripted output stands in for the key file."""
        return self.runner.run(
            "iam", "service-accounts", "keys", "create", "/dev/stdout",
            f"--iam-account={email}", "--format=json",
        )


def provision(kfdef, app_dir: str, client=None, *,
              runner: GcloudRunner | None = None) -> GcloudRunner:
    """Full apply flow from the generated gcp_config/ directory."""
    runner = runner or GcloudRunner()
    prov = GcpProvisioner(runner)
    cfg_dir = os.path.join(app_dir, "gcp_config")
    with open(os.path.join(cfg_dir, "cluster.yaml")) as f:
        cluster = yaml.safe_load(f)["cluster"]
    with open(os.path.join(cfg_dir, "iam_bindings.yaml")) as f:
        bindings = yaml.safe_load(f)["bindings"]

    prov.init_project(cluster["project"])
    prov.ensure_cluster(cluster)
    prov.apply_iam_bindings(cluster["project"], bindings)
    if client is not None:
        prov.bootstrap_k8s(client, kfdef)
    return runner


def _json(text: str, default):
    try:
        out = json.loads(text or "null")
    except ValueError:
        return default
    return out if out is not None else default
