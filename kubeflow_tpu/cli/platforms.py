"""Platform drivers: infra provisioning + cluster connection per platform.

The analogue of the platform KfApps — gcp (bootstrap/pkg/kfapp/gcp/gcp.go:
generateDMConfigs :951, updateDM :480, blockingWait :221), minikube
(minikube.go:44-138) — recast for TPU:

- ``fake``     : in-process FakeApiServer (tests, dry-run deploys)
- ``none``     : bring-your-own cluster, connect via kubectl-proxy/KUBECONFIG
- ``minikube`` : local cluster via kubectl proxy
- ``gcp-tpu``  : writes TPU cluster provisioning configs (the
  cluster-kubeflow.yaml/cluster.jinja analogue with TPU slice node pools
  replacing the GPU pool at cluster.jinja:132-158) and shells out to gcloud
  when available.
"""

from __future__ import annotations

import logging
import os
import shutil
import subprocess

import yaml

from kubeflow_tpu.config.kfdef import (
    KfDef,
    PLATFORM_FAKE,
    PLATFORM_GCP_TPU,
    PLATFORM_MINIKUBE,
    PLATFORM_NONE,
)
from kubeflow_tpu.k8s.client import ClusterConfig, HttpK8sClient, K8sClient
from kubeflow_tpu.k8s.fake import FakeApiServer

logger = logging.getLogger(__name__)


class Platform:
    """Platform driver interface (KfApp Init/Generate/Apply/Delete analogue
    restricted to the infra half; manifests are the coordinator's job)."""

    name = "base"

    def generate(self, kfdef: KfDef, app_dir: str) -> None:
        """Write platform config files into the app dir."""

    def apply(self, kfdef: KfDef) -> None:
        """Provision/verify infrastructure."""

    def client(self, kfdef: KfDef) -> K8sClient:
        raise NotImplementedError


class FakePlatform(Platform):
    """In-process cluster. One FakeApiServer per process, shared across
    coordinator instances so apply/show/delete see the same state."""

    name = PLATFORM_FAKE
    _shared: FakeApiServer | None = None

    @classmethod
    def shared_server(cls) -> FakeApiServer:
        if cls._shared is None:
            cls._shared = FakeApiServer()
        return cls._shared

    @classmethod
    def reset(cls) -> None:
        cls._shared = None

    def client(self, kfdef: KfDef) -> K8sClient:
        return self.shared_server()


class NonePlatform(Platform):
    """User brings a cluster; we connect via $KUBEFLOW_TPU_APISERVER or the
    kubectl-proxy default."""

    name = PLATFORM_NONE

    def client(self, kfdef: KfDef) -> K8sClient:
        host = os.environ.get("KUBEFLOW_TPU_APISERVER", "http://127.0.0.1:8001")
        token = os.environ.get("KUBEFLOW_TPU_TOKEN")
        return HttpK8sClient(ClusterConfig(host=host, token=token))


class MinikubePlatform(NonePlatform):
    name = PLATFORM_MINIKUBE

    def apply(self, kfdef: KfDef) -> None:
        if shutil.which("minikube") is None:
            logger.warning("minikube binary not found; assuming cluster is already up")
            return
        status = subprocess.run(
            ["minikube", "status", "--format", "{{.Host}}"],
            capture_output=True,
            text=True,
        )
        if "Running" not in status.stdout:
            raise RuntimeError("minikube is not running; `minikube start` first")


class GcpTpuPlatform(NonePlatform):
    """GKE + TPU node pools.

    generate() writes cluster provisioning configs into
    <app_dir>/gcp_config/ (the generateDMConfigs analogue, gcp.go:951):
    a cluster spec with a TPU slice node pool per KfDef.spec.tpu — this is
    the file a user feeds to gcloud/terraform. apply() runs gcloud when
    installed, else instructs.
    """

    name = PLATFORM_GCP_TPU

    def generate(self, kfdef: KfDef, app_dir: str) -> None:
        cfg_dir = os.path.join(app_dir, "gcp_config")
        os.makedirs(cfg_dir, exist_ok=True)
        tpu = kfdef.spec.tpu
        cluster = {
            "cluster": {
                "name": kfdef.name,
                "project": kfdef.spec.project,
                "zone": kfdef.spec.zone,
                "releaseChannel": "regular",
                # CPU pool for platform components (cluster-kubeflow.yaml:47
                # analogue)
                "nodePools": [
                    {
                        "name": "platform-pool",
                        "machineType": "n2-standard-8",
                        "initialNodeCount": 2,
                        "autoscaling": {"enabled": True, "minNodeCount": 2, "maxNodeCount": 10},
                    },
                    # TPU slice pool — replaces the GPU pool
                    # (cluster.jinja:132-158). One node per TPU VM host;
                    # gke placement policy keeps slices contiguous.
                    {
                        "name": "tpu-pool",
                        "machineType": _tpu_machine_type(tpu.accelerator),
                        "initialNodeCount": 0,
                        "autoscaling": {"enabled": True, "minNodeCount": 0, "maxNodeCount": 32},
                        "placementPolicy": {"tpuTopology": tpu.topology},
                        "config": {
                            "reservationAffinity": (
                                {"consumeReservationType": "ANY_RESERVATION"}
                                if tpu.reserved
                                else {"consumeReservationType": "NO_RESERVATION"}
                            ),
                            "labels": {
                                "kubeflow-tpu.org/accelerator": tpu.accelerator,
                            },
                        },
                        "multislice": {"numSlices": tpu.num_slices},
                    },
                ],
            }
        }
        with open(os.path.join(cfg_dir, "cluster.yaml"), "w") as f:
            yaml.safe_dump(cluster, f, sort_keys=False)
        iam = {
            "bindings": [
                {
                    "role": "roles/tpu.admin",
                    "members": [f"serviceAccount:{kfdef.name}-admin"
                                f"@{kfdef.spec.project}.iam.gserviceaccount.com"],
                },
                {
                    "role": "roles/logging.logWriter",
                    "members": [f"serviceAccount:{kfdef.name}-vm"
                                f"@{kfdef.spec.project}.iam.gserviceaccount.com"],
                },
            ]
        }
        with open(os.path.join(cfg_dir, "iam_bindings.yaml"), "w") as f:
            yaml.safe_dump(iam, f, sort_keys=False)

    def apply(self, kfdef: KfDef) -> None:
        """Full provisioning flow (gcp.go Apply semantics): enable service
        APIs, create cluster + TPU node pools with blocking waits, bind IAM
        roles, bootstrap the namespace/admin-binding/SA-secret. Without
        gcloud installed this degrades to a dry run that logs the exact
        command sequence (the preview the reference prints via DM configs).
        """
        from kubeflow_tpu.cli.gcp import GcloudRunner, provision

        dry = shutil.which("gcloud") is None
        runner = GcloudRunner(dry_run=dry)
        client = None if dry else self.client(kfdef)
        provision(kfdef, kfdef.spec.app_dir, client, runner=runner)
        if dry:
            logger.warning(
                "gcloud not installed - dry run; would have executed:\n%s",
                "\n".join("  " + " ".join(argv) for argv in runner.history),
            )


_PLATFORMS: dict[str, Platform] = {
    p.name: p()
    for p in (FakePlatform, NonePlatform, MinikubePlatform, GcpTpuPlatform)
}


def get_platform(name: str) -> Platform:
    try:
        return _PLATFORMS[name]
    except KeyError:
        raise ValueError(f"unknown platform {name!r}; known: {sorted(_PLATFORMS)}")


def _tpu_machine_type(accelerator: str) -> str:
    """Map TPU accelerator type to the GKE machine type family."""
    if accelerator.startswith("v5litepod"):
        return "ct5lp-hightpu-4t"
    if accelerator.startswith("v5p"):
        return "ct5p-hightpu-4t"
    if accelerator.startswith("v4"):
        return "ct4p-hightpu-4t"
    if accelerator.startswith("v6e"):
        return "ct6e-standard-4t"
    return "ct5lp-hightpu-4t"
