"""Deployment coordinator: composes a platform driver with the manifest layer.

The analogue of bootstrap/pkg/kfapp/coordinator/coordinator.go — NewKfApp
(:227), LoadKfApp (:321), Generate (:492), Apply (:385) — plus the ksonnet
package-manager apply semantics (per-component apply with constant-backoff
retry, bootstrap/pkg/kfapp/ksonnet/ksonnet.go:132-175).

Lifecycle (4 verbs, KfApp interface analogue, group.go:93-98):

- init:     write app.yaml (KfDef) into a fresh app dir
- generate: render every component's manifests to <app>/manifests/<name>.yaml
            (+ platform config, e.g. TPU node-pool specs for gcp-tpu)
- apply:    platform.apply (provision infra) then apply manifests to the
            cluster: namespaces/CRDs first, then per-component with retry
- delete:   reverse: delete components, then optionally cluster-scoped
            resources + CRDs (the kfctl.sh:511-583 GC flow)
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable

import yaml

from kubeflow_tpu.config.kfdef import KfDef, PLATFORM_FAKE
from kubeflow_tpu.k8s.client import ApiError, K8sClient
from kubeflow_tpu.manifests.core import generate as generate_prototype

logger = logging.getLogger(__name__)

# Per-component apply retry: 6 attempts, constant 5s backoff
# (ksonnet.go:147-168 semantics).
APPLY_RETRIES = 6
APPLY_BACKOFF_SECONDS = 5.0

# Kinds applied before everything else, in order.
_PRIORITY_KINDS = ("Namespace", "CustomResourceDefinition")
# Cluster-scoped kinds garbage-collected on `delete all` (kfctl.sh:529-557
# deletes clusterrolebindings/clusterroles/crds by label).
_CLUSTER_SCOPED_GC_KINDS = (
    "ClusterRoleBinding",
    "ClusterRole",
    "MutatingWebhookConfiguration",
    "ValidatingWebhookConfiguration",
    "CustomResourceDefinition",
)

PART_OF_LABEL = "app.kubernetes.io/part-of"
PLATFORM_LABEL_VALUE = "kubeflow-tpu"


@dataclass
class ApplyReport:
    applied: list[str] = field(default_factory=list)
    failed: dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.failed


class Coordinator:
    def __init__(
        self,
        kfdef: KfDef,
        client_factory: Callable[[KfDef], K8sClient] | None = None,
        backoff_seconds: float | None = None,
    ):
        self.kfdef = kfdef
        self._client_factory = client_factory or _default_client_factory
        self._client: K8sClient | None = None
        self._backoff = (
            backoff_seconds
            if backoff_seconds is not None
            else (0.0 if kfdef.spec.platform == PLATFORM_FAKE else APPLY_BACKOFF_SECONDS)
        )

    # ------------------------------------------------------------------
    # verbs
    # ------------------------------------------------------------------

    @classmethod
    def init(cls, kfdef: KfDef, app_dir: str, **kwargs) -> "Coordinator":
        """Create the app dir and persist app.yaml (NewKfApp analogue)."""
        os.makedirs(app_dir, exist_ok=True)
        app_yaml = os.path.join(app_dir, "app.yaml")
        if os.path.exists(app_yaml):
            raise FileExistsError(f"{app_yaml} already exists; delete it or use a new dir")
        kfdef.spec.app_dir = app_dir
        kfdef.save(app_yaml)
        return cls(kfdef, **kwargs)

    @classmethod
    def load(cls, app_dir: str, **kwargs) -> "Coordinator":
        return cls(KfDef.load_app_dir(app_dir), **kwargs)

    def generate(self, what: str = "all") -> list[str]:
        """Render component manifests and/or platform config into the app dir.

        ``what`` scopes the verb like the reference CLI
        (kfctl {generate,apply,delete} {all,k8s,platform}, root.go:23-40):
        ``k8s`` renders manifests only, ``platform`` writes platform config
        only, ``all`` does both.
        """
        app_dir = self._require_app_dir()
        written: list[str] = []
        if what in ("all", "k8s"):
            mdir = os.path.join(app_dir, "manifests")
            os.makedirs(mdir, exist_ok=True)
            for comp in self.kfdef.spec.components:
                params = dict(comp.params)
                objs = generate_prototype(comp.prototype_name, self._with_defaults(params))
                if comp.overlay:
                    from kubeflow_tpu.manifests.overlays import (
                        Overlay,
                        apply_overlay,
                    )

                    objs = apply_overlay(objs, Overlay.from_dict(comp.overlay))
                self._label_objects(objs)
                path = os.path.join(mdir, f"{comp.name}.yaml")
                with open(path, "w") as f:
                    yaml.safe_dump_all(objs, f, sort_keys=True)
                written.append(path)
        if what in ("all", "platform"):
            self._generate_platform_config(app_dir)
        return written

    def apply(self, what: str = "all") -> ApplyReport:
        """Provision platform (what=all|platform) and apply generated
        manifests (what=all|k8s)."""
        if what in ("all", "platform"):
            self._platform_apply()
        if what == "platform":
            return ApplyReport()
        client = self.client()
        report = ApplyReport()
        ns = self.kfdef.spec.namespace
        # namespace first (ksonnet.go:102-110)
        try:
            if client.get_or_none("v1", "Namespace", ns) is None:
                client.create(
                    {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}}
                )
        except ApiError as e:
            report.failed["namespace"] = str(e)
            return report

        components = self._load_generated()
        # global pass: priority kinds across all components (CRDs must exist
        # before CRs referencing them)
        for kind in _PRIORITY_KINDS:
            for comp_name, objs in components:
                for obj in objs:
                    if obj["kind"] == kind:
                        self._apply_one(client, obj, comp_name, report)
        for comp_name, objs in components:
            for obj in objs:
                if obj["kind"] in _PRIORITY_KINDS:
                    continue
                self._apply_one(client, obj, comp_name, report)
        return report

    def delete(self, what: str = "all", delete_cluster_scoped: bool = True) -> ApplyReport:
        """Delete deployed components (kfctl.sh:511-583 delete flow).

        ``what=platform`` is a no-op today: cluster deprovisioning is left to
        the user's infra tooling (parity with `kfctl delete platform`, which
        the reference also gates behind confirmation)."""
        if what == "platform":
            return ApplyReport()
        client = self.client()
        report = ApplyReport()
        components = self._load_generated()
        for comp_name, objs in components:
            for obj in objs:
                if obj["kind"] in _CLUSTER_SCOPED_GC_KINDS:
                    continue
                m = obj["metadata"]
                try:
                    client.delete_if_exists(
                        obj["apiVersion"], obj["kind"], m["name"], m.get("namespace")
                    )
                    report.applied.append(f"{comp_name}/{obj['kind']}/{m['name']}")
                except ApiError as e:
                    report.failed[f"{comp_name}/{obj['kind']}/{m['name']}"] = str(e)
        if delete_cluster_scoped:
            for comp_name, objs in components:
                for kind in _CLUSTER_SCOPED_GC_KINDS:
                    for obj in objs:
                        if obj["kind"] != kind:
                            continue
                        m = obj["metadata"]
                        try:
                            client.delete_if_exists(obj["apiVersion"], kind, m["name"])
                            report.applied.append(f"{comp_name}/{kind}/{m['name']}")
                        except ApiError as e:
                            report.failed[f"{comp_name}/{kind}/{m['name']}"] = str(e)
        return report

    def show(self) -> list[dict]:
        """All generated objects (ks show analogue)."""
        out: list[dict] = []
        for _, objs in self._load_generated():
            out.extend(objs)
        return out

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def client(self) -> K8sClient:
        if self._client is None:
            self._client = self._client_factory(self.kfdef)
        return self._client

    def _require_app_dir(self) -> str:
        if not self.kfdef.spec.app_dir:
            raise ValueError("KfDef has no app_dir; use Coordinator.init/load")
        return self.kfdef.spec.app_dir

    def _with_defaults(self, params: dict) -> dict:
        params.setdefault("namespace", self.kfdef.spec.namespace)
        return params

    def _label_objects(self, objs: list[dict]) -> None:
        for obj in objs:
            labels = obj["metadata"].setdefault("labels", {})
            labels.setdefault(PART_OF_LABEL, PLATFORM_LABEL_VALUE)

    def _load_generated(self) -> list[tuple[str, list[dict]]]:
        app_dir = self._require_app_dir()
        mdir = os.path.join(app_dir, "manifests")
        if not os.path.isdir(mdir):
            raise FileNotFoundError(
                f"{mdir} does not exist; run `kfctl generate` first"
            )
        out = []
        for comp in self.kfdef.spec.components:
            path = os.path.join(mdir, f"{comp.name}.yaml")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"{path} missing; re-run `kfctl generate` (component {comp.name})"
                )
            with open(path) as f:
                objs = [o for o in yaml.safe_load_all(f) if o]
            out.append((comp.name, objs))
        return out

    def _apply_one(
        self, client: K8sClient, obj: dict, comp_name: str, report: ApplyReport
    ) -> None:
        m = obj["metadata"]
        key = f"{comp_name}/{obj['kind']}/{m['name']}"
        last_err: Exception | None = None
        for attempt in range(APPLY_RETRIES):
            try:
                client.apply(obj)
                report.applied.append(key)
                return
            except ApiError as e:
                last_err = e
                # 4xx (other than 409 conflict races and transient
                # 429/408 load-shedding) won't heal by retrying
                if 400 <= e.code < 500 and e.code != 409 and not e.transient:
                    break
                logger.warning("apply %s attempt %d failed: %s", key, attempt + 1, e)
                if self._backoff:
                    time.sleep(self._backoff)
            except Exception as e:  # network-level errors: retry
                last_err = e
                logger.warning("apply %s attempt %d failed: %s", key, attempt + 1, e)
                if self._backoff:
                    time.sleep(self._backoff)
        report.failed[key] = str(last_err)

    # ------------------------------------------------------------------
    # platform drivers
    # ------------------------------------------------------------------

    def _platform_apply(self) -> None:
        from kubeflow_tpu.cli import platforms

        platforms.get_platform(self.kfdef.spec.platform).apply(self.kfdef)

    def _generate_platform_config(self, app_dir: str) -> None:
        from kubeflow_tpu.cli import platforms

        platforms.get_platform(self.kfdef.spec.platform).generate(self.kfdef, app_dir)


def _default_client_factory(kfdef: KfDef) -> K8sClient:
    from kubeflow_tpu.cli import platforms

    return platforms.get_platform(kfdef.spec.platform).client(kfdef)
