"""kfctl: the deployment CLI.

The analogue of bootstrap/cmd/kfctl (cobra commands
init/generate/apply/delete/show/version, root.go:23-40) and scripts/kfctl.sh.

Usage:
    kfctl init <app-name> --platform gcp-tpu --project p --zone us-central2-b
    kfctl generate [all|k8s|platform]
    kfctl apply    [all|k8s|platform]
    kfctl delete   [all|k8s]
    kfctl show
    kfctl version

State lives in <app-dir>/app.yaml (KfDef), like the reference's app.yaml +
env.sh persistence (kfctl.sh:44-75).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

import yaml

from kubeflow_tpu.cli.coordinator import Coordinator
from kubeflow_tpu.config import defaults
from kubeflow_tpu.config.kfdef import ALLOWED_PLATFORMS
from kubeflow_tpu.version import __version__


def _add_init(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser("init", help="create a new kubeflow-tpu app dir")
    p.add_argument("name", help="app name (also the app dir unless --app-dir)")
    p.add_argument("--app-dir", default=None)
    p.add_argument("--platform", default="none", choices=ALLOWED_PLATFORMS)
    p.add_argument("--namespace", default="kubeflow")
    p.add_argument("--project", default="", help="cloud project (gcp-tpu)")
    p.add_argument("--zone", default="")
    p.add_argument("--accelerator", default="v5litepod-8")
    p.add_argument("--topology", default="2x4")
    p.add_argument("--num-slices", type=int, default=1)
    p.add_argument("--use-basic-auth", action="store_true")


def _add_verb(sub: argparse._SubParsersAction, verb: str, help_: str) -> None:
    p = sub.add_parser(verb, help=help_)
    p.add_argument(
        "what",
        nargs="?",
        default="all",
        choices=["all", "k8s", "platform"],
        help="scope (reference kfctl semantics)",
    )
    p.add_argument("--app-dir", default=".", help="app dir (default: cwd)")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kfctl", description=__doc__)
    ap.add_argument("-v", "--verbose", action="store_true")
    sub = ap.add_subparsers(dest="command", required=True)
    _add_init(sub)
    _add_verb(sub, "generate", "render component manifests into the app dir")
    _add_verb(sub, "apply", "provision platform and apply manifests")
    _add_verb(sub, "delete", "delete deployed resources")
    show = sub.add_parser("show", help="print generated manifests")
    show.add_argument("--app-dir", default=".")
    sub.add_parser("version", help="print version")
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(levelname)s %(name)s: %(message)s",
    )
    try:
        return _dispatch(args)
    except (ValueError, FileNotFoundError, FileExistsError, RuntimeError) as e:
        print(f"kfctl: error: {e}", file=sys.stderr)
        return 1


def _dispatch(args: argparse.Namespace) -> int:
    if args.command == "version":
        print(__version__)
        return 0

    if args.command == "init":
        app_dir = args.app_dir or os.path.abspath(args.name)
        kfdef = defaults.default_kfdef(
            args.name,
            platform=args.platform,
            namespace=args.namespace,
            project=args.project,
            zone=args.zone,
            accelerator=args.accelerator,
            topology=args.topology,
            num_slices=args.num_slices,
            use_basic_auth=args.use_basic_auth,
        )
        Coordinator.init(kfdef, app_dir)
        print(f"initialized app {args.name!r} in {app_dir} (platform={args.platform})")
        print(f"components: {', '.join(c.name for c in kfdef.spec.components)}")
        return 0

    coord = Coordinator.load(os.path.abspath(args.app_dir))

    if args.command == "generate":
        written = coord.generate(args.what)
        for path in written:
            print(f"generated {os.path.relpath(path)}")
        return 0

    if args.command == "apply":
        if args.what in ("all", "k8s") and not os.path.isdir(
            os.path.join(coord.kfdef.spec.app_dir, "manifests")
        ):
            coord.generate(args.what)
        report = coord.apply(args.what)
        print(f"applied {len(report.applied)} objects")
        if report.failed:
            for key, err in report.failed.items():
                print(f"FAILED {key}: {err}", file=sys.stderr)
            return 1
        return 0

    if args.command == "delete":
        report = coord.delete(args.what)
        print(f"deleted {len(report.applied)} objects")
        if report.failed:
            for key, err in report.failed.items():
                print(f"FAILED {key}: {err}", file=sys.stderr)
            return 1
        return 0

    if args.command == "show":
        objs = coord.show()
        sys.stdout.write(yaml.safe_dump_all(objs, sort_keys=True))
        return 0

    raise ValueError(f"unknown command {args.command}")


if __name__ == "__main__":
    raise SystemExit(main())
