"""CLI: `python -m kubeflow_tpu.bootstrap --port 8085 --work-dir /apps`
(the bootstrapper Deployment entrypoint, bootstrap/cmd/bootstrap/main.go)."""

from __future__ import annotations

import argparse
import threading

from kubeflow_tpu.bootstrap.service import BootstrapService
from kubeflow_tpu.config.kfdef import PLATFORM_NONE


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=8085)
    ap.add_argument("--work-dir", default="/var/lib/kubeflow-tpu/apps")
    # In-cluster default is the real apiserver; "fake" is for dry runs.
    ap.add_argument("--default-platform", default=PLATFORM_NONE)
    args = ap.parse_args(argv)
    service = BootstrapService(args.work_dir,
                               default_platform=args.default_platform)
    _httpd, port = service.serve(args.port)
    print(f"bootstrapper listening on :{port} (apps in {args.work_dir})")
    threading.Event().wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
