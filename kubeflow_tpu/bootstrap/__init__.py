"""Bootstrapper REST service — the in-cluster deploy API.

Analogue of bootstrap/cmd/bootstrap/app/ksServer.go (routes at
:1452-1460): the HTTP service that the click-to-deploy web flow drives,
wrapping the coordinator's init/generate/apply lifecycle with per-app
serialization and a /metrics surface.
"""

from kubeflow_tpu.bootstrap.service import BootstrapService  # noqa: F401
