"""Bootstrapper REST service.

The ksServer analogue (bootstrap/cmd/bootstrap/app/ksServer.go): a deploy
API that creates and applies platform apps on request, with the same route
shape and semantics —

- ``POST /kfctl/apps/create``  {name, platform?, project?, zone?, params?}
  → init the app dir + generate manifests (CreateApp, ksServer.go:432)
- ``POST /kfctl/apps/apply``   {name, what?} → apply (Apply, :1037)
- ``POST /kfctl/e2eDeploy``    create+apply in one call (the click-to-deploy
  entry, routes :1452-1460)
- ``GET  /kfctl/apps``         list known apps + status
- ``GET  /healthz``, ``GET /metrics`` (promhttp analogue, :1460)

Per-app mutexes serialize concurrent deploys of the same app
(ksServer.go:384's per-project sync.Mutex); different apps deploy
concurrently. Apps live under ``--work-dir`` as ordinary kfctl app dirs, so
the CLI and this service are interchangeable views of the same state.

Entrypoint: ``python -m kubeflow_tpu.bootstrap``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from kubeflow_tpu.cli.coordinator import Coordinator
from kubeflow_tpu.config.defaults import default_kfdef
from kubeflow_tpu.config.kfdef import PLATFORM_NONE
from kubeflow_tpu.observability.metrics import render_prometheus

# Click-to-deploy page (the gcp-click-to-deploy React SPA's role,
# components/gcp-click-to-deploy/src/DeployForm.tsx, server-rendered):
# one form driving POST /kfctl/e2eDeploy.
_DEPLOY_PAGE = """<!doctype html>
<html><head><title>kubeflow-tpu deploy</title>
<style>body{font-family:sans-serif;margin:2rem;max-width:40rem}
label{display:block;margin:.5rem 0}input{width:100%}</style></head>
<body><h1>Deploy kubeflow-tpu</h1>
<form id="f">
  <label>Deployment name <input name="name" value="kubeflow" required></label>
  <label>Platform <input name="platform" placeholder="none | gcp-tpu"></label>
  <label>GCP project <input name="project"></label>
  <label>Zone <input name="zone" placeholder="us-central2-b"></label>
  <button type="submit">Create deployment</button>
</form>
<pre id="out"></pre>
<script>
document.getElementById('f').addEventListener('submit', async (e) => {
  e.preventDefault();
  const body = Object.fromEntries(new FormData(e.target).entries());
  for (const k of Object.keys(body)) if (!body[k]) delete body[k];
  const out = document.getElementById('out');
  out.textContent = 'deploying...';
  const resp = await fetch('/kfctl/e2eDeploy', {
    method: 'POST', headers: {'Content-Type': 'application/json'},
    body: JSON.stringify(body)});
  out.textContent = JSON.stringify(await resp.json(), null, 2);
});
</script></body></html>
"""


class BootstrapService:
    # Default platform is the real in-cluster apiserver; tests pass "fake".
    def __init__(self, work_dir: str, *, default_platform: str = PLATFORM_NONE):
        self.work_dir = Path(work_dir)
        self.work_dir.mkdir(parents=True, exist_ok=True)
        self.default_platform = default_platform
        self._locks: dict[str, threading.Lock] = defaultdict(threading.Lock)
        self._locks_guard = threading.Lock()
        self._status: dict[str, dict] = {}
        self._counter_lock = threading.Lock()
        self.requests = 0
        self.errors = 0

    def _count(self, *, error: bool = False) -> None:
        with self._counter_lock:  # handler threads race bare +=
            self.requests += 1
            self.errors += int(error)

    # ------------------------------------------------------------------
    # operations (HTTP-independent, used by tests and the handler)
    # ------------------------------------------------------------------

    def _lock_for(self, name: str) -> threading.Lock:
        with self._locks_guard:
            return self._locks[name]

    def _app_dir(self, name: str) -> Path:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"invalid app name {name!r}")
        return self.work_dir / name

    def create_app(self, body: dict) -> dict:
        name = body.get("name", "")
        app_dir = self._app_dir(name)
        with self._lock_for(name):
            if (app_dir / "app.yaml").exists():
                # Idempotent re-create so a retried e2eDeploy after a failed
                # apply doesn't wedge on FileExistsError: reload and
                # regenerate from the persisted app.yaml.
                coord = Coordinator.load(str(app_dir))
            else:
                kfdef = default_kfdef(
                    name=name,
                    platform=body.get("platform", self.default_platform),
                    project=body.get("project", ""),
                    zone=body.get("zone", ""),
                )
                coord = Coordinator.init(kfdef, str(app_dir))
            written = coord.generate("all")
            self._status[name] = {"phase": "Created",
                                  "manifests": len(written),
                                  "updated": time.time()}
            return {"name": name, "appDir": str(app_dir),
                    "manifests": len(written)}

    def apply_app(self, body: dict) -> dict:
        name = body.get("name", "")
        app_dir = self._app_dir(name)
        if not (app_dir / "app.yaml").exists():
            raise FileNotFoundError(f"app {name!r} not created")
        with self._lock_for(name):
            coord = Coordinator.load(str(app_dir))
            report = coord.apply(body.get("what", "all"))
            self._status[name] = {
                "phase": "Deployed" if report.ok else "Failed",
                "applied": len(report.applied),
                "failed": dict(report.failed),
                "updated": time.time(),
            }
            if not report.ok:
                raise RuntimeError(
                    f"apply failed for: {sorted(report.failed)}"
                )
            return {"name": name, "applied": len(report.applied)}

    def e2e_deploy(self, body: dict) -> dict:
        created = self.create_app(body)
        applied = self.apply_app({"name": body.get("name", "")})
        return {**created, **applied, "phase": "Deployed"}

    def list_apps(self) -> dict:
        apps = []
        for app_yaml in sorted(self.work_dir.glob("*/app.yaml")):
            name = app_yaml.parent.name
            apps.append({"name": name,
                         **self._status.get(name, {"phase": "Created"})})
        return {"apps": apps}

    def metrics(self) -> str:
        deployed = sum(1 for s in self._status.values()
                       if s.get("phase") == "Deployed")
        # Snapshot both counters under their lock so the rendered pair
        # is consistent (requests >= errors must hold in every scrape).
        with self._counter_lock:
            requests, errors = self.requests, self.errors
        return render_prometheus({
            "bootstrap_requests_total": requests,
            "bootstrap_errors_total": errors,
            "bootstrap_apps_deployed": deployed,
        })

    # ------------------------------------------------------------------
    # HTTP
    # ------------------------------------------------------------------

    def make_handler(service: "BootstrapService"):
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _send(self, code: int, payload, content_type="application/json"):
                body = (payload if isinstance(payload, str)
                        else json.dumps(payload)).encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/", "/deploy"):
                    service._count()
                    self._send(200, _DEPLOY_PAGE, "text/html")
                elif self.path == "/healthz":
                    service._count()
                    self._send(200, {"status": "ok"})
                elif self.path == "/metrics":
                    service._count()
                    self._send(200, service.metrics(), "text/plain")
                elif self.path == "/kfctl/apps":
                    service._count()
                    self._send(200, service.list_apps())
                else:
                    service._count(error=True)
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                routes = {
                    "/kfctl/apps/create": service.create_app,
                    "/kfctl/apps/apply": service.apply_app,
                    "/kfctl/e2eDeploy": service.e2e_deploy,
                }
                handler = routes.get(self.path)
                if handler is None:
                    service._count(error=True)
                    self._send(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    result = handler(body)
                    service._count()
                    self._send(200, result)
                except (ValueError, FileNotFoundError,
                        FileExistsError) as e:
                    service._count(error=True)
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    service._count(error=True)
                    self._send(500, {"error": str(e)})

        return Handler

    def serve(self, port: int = 0) -> tuple[ThreadingHTTPServer, int]:
        httpd = ThreadingHTTPServer(("0.0.0.0", port), self.make_handler())
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, httpd.server_address[1]
