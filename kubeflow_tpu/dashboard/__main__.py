"""Central-dashboard entrypoint: `python -m kubeflow_tpu.dashboard`
(components/centraldashboard analogue, serving on :8082)."""

from __future__ import annotations

import argparse
import sys

from kubeflow_tpu.dashboard import Dashboard, make_server
from kubeflow_tpu.runtime import add_client_args, client_from_args, strip_glog_args


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="kubeflow-tpu central dashboard")
    add_client_args(p)
    p.add_argument("--port", type=int, default=8082)
    p.add_argument("--all-namespaces", action="store_true",
                   help="aggregate across all namespaces")
    args = p.parse_args(argv)

    dash = Dashboard(client_from_args(args),
                     None if args.all_namespaces else args.namespace)
    httpd = make_server(dash, args.port)
    print(f"dashboard on :{args.port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
