"""Training dashboard: `python -m kubeflow_tpu.dashboard.training`.

The tf-job-dashboard analogue (kubeflow/tf-training/
tf-job-operator.libsonnet:353-488): jobs across all six kinds with replica
status, conditions, and published metrics.

- ``GET /api/jobs``                      all jobs (all kinds)
- ``GET /api/namespaces/<ns>/jobs``      jobs in one namespace
- ``GET /``                              HTML table
- ``GET /healthz``
"""

from __future__ import annotations

import argparse
import html
import re
import sys
from http.server import ThreadingHTTPServer

from kubeflow_tpu.apis.jobs import ALL_JOB_KINDS, JOBS_API_VERSION
from kubeflow_tpu.k8s.client import ApiError, K8sClient
from kubeflow_tpu.runtime import add_client_args, client_from_args, strip_glog_args
from kubeflow_tpu.webapps import JsonHandler

_RE_NS = re.compile(r"^/api/namespaces/([^/]+)/jobs/?$")

_PAGE = """<!doctype html>
<html><head><title>training jobs</title>
<style>body{{font-family:sans-serif;margin:2rem}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:4px 10px}}</style></head>
<body><h1>Training jobs</h1>
<table><tr><th>Kind</th><th>Name</th><th>Namespace</th><th>State</th>
<th>Replicas</th><th>Metrics</th></tr>{rows}</table></body></html>
"""


class TrainingDashboard:
    def __init__(self, client: K8sClient):
        self.client = client

    def jobs(self, namespace: str | None = None) -> list[dict]:
        out = []
        for kind in ALL_JOB_KINDS:
            try:
                items = self.client.list(JOBS_API_VERSION, kind, namespace)
            except ApiError:
                continue
            for job in items:
                status = job.get("status", {})
                out.append({
                    "kind": kind,
                    "name": job["metadata"]["name"],
                    "namespace": job["metadata"]["namespace"],
                    "state": status.get("state", "Unknown"),
                    "replicaStatuses": status.get("replicaStatuses", {}),
                    "conditions": status.get("conditions", []),
                    "metrics": status.get("metrics", {}),
                    "restartCount": status.get("restartCount", 0),
                })
        return out

    def render_html(self) -> str:
        rows = "".join(
            "<tr>"
            f"<td>{html.escape(j['kind'])}</td>"
            f"<td>{html.escape(j['name'])}</td>"
            f"<td>{html.escape(j['namespace'])}</td>"
            f"<td>{html.escape(j['state'])}</td>"
            f"<td>{html.escape(str(j['replicaStatuses']))}</td>"
            f"<td>{html.escape(str(j['metrics']))}</td>"
            "</tr>"
            for j in self.jobs()
        )
        return _PAGE.format(rows=rows)


def make_server(dash: TrainingDashboard, port: int) -> ThreadingHTTPServer:
    class Handler(JsonHandler):
        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self.send_json(200, {"status": "ok"})
                return
            if self.path == "/api/jobs":
                self.send_json(200, {"jobs": dash.jobs()})
                return
            m = _RE_NS.match(self.path)
            if m:
                self.send_json(200, {"jobs": dash.jobs(m.group(1))})
                return
            if self.path in ("/", "/index.html"):
                self.send_html(200, dash.render_html())
                return
            self.send_json(404, {"error": f"no route {self.path}"})

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="training-job dashboard")
    add_client_args(p)
    p.add_argument("--port", type=int, default=8085)
    args = p.parse_args(argv)

    httpd = make_server(TrainingDashboard(client_from_args(args)), args.port)
    print(f"training dashboard on :{args.port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
