"""Central dashboard: platform overview UI + JSON API.

The centraldashboard analogue (components/centraldashboard/app/server.ts +
k8s_service.ts): aggregates component links (Services carrying gateway-route
annotations), training jobs, notebooks, and studies into one landing page.
"""

from __future__ import annotations

import html
from http.server import ThreadingHTTPServer

from kubeflow_tpu.apis.jobs import ALL_JOB_KINDS, JOBS_API_VERSION
from kubeflow_tpu.apis.notebooks import NOTEBOOK_KIND, NOTEBOOKS_API_VERSION
from kubeflow_tpu.apis.tuning import STUDY_JOB_KIND, TUNING_API_VERSION
from kubeflow_tpu.gateway import routes_from_service
from kubeflow_tpu.k8s.client import ApiError, K8sClient
from kubeflow_tpu.operators.runstore import RunStore
from kubeflow_tpu.webapps import JsonHandler

_PAGE = """<!doctype html>
<html><head><title>kubeflow-tpu</title>
<style>body{{font-family:sans-serif;margin:2rem}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:4px 10px}}</style></head>
<body><h1>kubeflow-tpu</h1>
<h2>Components</h2><ul>{components}</ul>
<h2>Jobs</h2><table><tr><th>Kind</th><th>Name</th><th>Namespace</th>
<th>State</th></tr>{jobs}</table>
<h2>Notebooks</h2><table><tr><th>Name</th><th>Namespace</th><th>State</th>
</tr>{notebooks}</table>
<h2>Studies</h2><table><tr><th>Name</th><th>Namespace</th><th>State</th>
<th>Best</th></tr>{studies}</table>
<h2>Pipeline runs</h2><table><tr><th>Workflow</th><th>Schedule</th>
<th>Phase</th><th>Started</th><th>Finished</th></tr>{runs}</table>
</body></html>
"""


class Dashboard:
    def __init__(self, client: K8sClient, namespace: str | None = None):
        self.client = client
        self.namespace = namespace

    def _safe_list(self, api_version: str, kind: str) -> list[dict]:
        try:
            return self.client.list(api_version, kind, self.namespace)
        except ApiError:
            return []

    def components(self) -> list[dict]:
        out = []
        for svc in self._safe_list("v1", "Service"):
            for route in routes_from_service(svc):
                out.append({"name": route.name, "prefix": route.prefix,
                            "service": route.service})
        return out

    def jobs(self) -> list[dict]:
        out = []
        for kind in ALL_JOB_KINDS:
            for job in self._safe_list(JOBS_API_VERSION, kind):
                out.append({
                    "kind": kind,
                    "name": job["metadata"]["name"],
                    "namespace": job["metadata"]["namespace"],
                    "state": job.get("status", {}).get("state", "Unknown"),
                })
        return out

    def notebooks(self) -> list[dict]:
        return [{
            "name": nb["metadata"]["name"],
            "namespace": nb["metadata"]["namespace"],
            "state": nb.get("status", {}).get("state", "Unknown"),
        } for nb in self._safe_list(NOTEBOOKS_API_VERSION, NOTEBOOK_KIND)]

    def studies(self) -> list[dict]:
        return [{
            "name": s["metadata"]["name"],
            "namespace": s["metadata"]["namespace"],
            "state": s.get("status", {}).get("state", "Unknown"),
            "bestObjective": s.get("status", {}).get("bestObjective"),
        } for s in self._safe_list(TUNING_API_VERSION, STUDY_JOB_KIND)]

    def runs(self) -> list[dict]:
        """Workflow run history — outlives the Workflow CRs (RunStore,
        the pipeline-persistenceagent surface)."""
        try:
            return RunStore(self.client).list_runs(self.namespace)
        except ApiError:
            return []

    def overview(self) -> dict:
        return {
            "components": self.components(),
            "jobs": self.jobs(),
            "notebooks": self.notebooks(),
            "studies": self.studies(),
            "runs": self.runs(),
        }

    def render_html(self) -> str:
        ov = self.overview()

        def esc(v) -> str:
            return html.escape(str(v))

        components = "".join(
            f"<li><a href=\"{esc(c['prefix'])}\">{esc(c['name'])}</a> "
            f"→ {esc(c['service'])}</li>" for c in ov["components"]
        ) or "<li>(none)</li>"
        jobs = "".join(
            f"<tr><td>{esc(j['kind'])}</td><td>{esc(j['name'])}</td>"
            f"<td>{esc(j['namespace'])}</td><td>{esc(j['state'])}</td></tr>"
            for j in ov["jobs"]
        )
        notebooks = "".join(
            f"<tr><td>{esc(n['name'])}</td><td>{esc(n['namespace'])}</td>"
            f"<td>{esc(n['state'])}</td></tr>" for n in ov["notebooks"]
        )
        studies = "".join(
            f"<tr><td>{esc(s['name'])}</td><td>{esc(s['namespace'])}</td>"
            f"<td>{esc(s['state'])}</td><td>{esc(s['bestObjective'])}</td>"
            "</tr>" for s in ov["studies"]
        )
        runs = "".join(
            f"<tr><td>{esc(r['workflow'])}</td>"
            f"<td>{esc(r.get('scheduledWorkflow', ''))}</td>"
            f"<td>{esc(r['phase'])}</td><td>{esc(r.get('startedAt', ''))}"
            f"</td><td>{esc(r.get('finishedAt', ''))}</td></tr>"
            for r in ov["runs"]
        )
        return _PAGE.format(components=components, jobs=jobs,
                            notebooks=notebooks, studies=studies,
                            runs=runs)


def make_server(dash: Dashboard, port: int) -> ThreadingHTTPServer:
    class Handler(JsonHandler):
        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self.send_json(200, {"status": "ok"})
            elif self.path == "/api/overview":
                self.send_json(200, dash.overview())
            elif self.path == "/api/runs":
                self.send_json(200, {"runs": dash.runs()})
            elif self.path in ("/", "/index.html"):
                self.send_html(200, dash.render_html())
            else:
                self.send_json(404, {"error": f"no route {self.path}"})

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)
