"""Central dashboard: platform overview UI + JSON API.

The centraldashboard analogue (components/centraldashboard/app/server.ts +
k8s_service.ts): aggregates component links (Services carrying gateway-route
annotations), training jobs, notebooks, and studies into one landing page,
with the namespace selector and activity feed of the reference's SPA
(components/centraldashboard/public/components/namespace-selector.js,
dashboard-view.js) served as query-filtered HTML + JSON.
"""

from __future__ import annotations

import html
from http.server import ThreadingHTTPServer
from urllib.parse import parse_qs, quote, unquote, urlsplit

from kubeflow_tpu.apis.jobs import ALL_JOB_KINDS, JOBS_API_VERSION
from kubeflow_tpu.apis.notebooks import NOTEBOOK_KIND, NOTEBOOKS_API_VERSION
from kubeflow_tpu.apis.pipelines import PIPELINES_API_VERSION, WORKFLOW_KIND
from kubeflow_tpu.apis.tuning import STUDY_JOB_KIND, TUNING_API_VERSION
from kubeflow_tpu.gateway import routes_from_service
from kubeflow_tpu.k8s.client import ApiError, K8sClient
from kubeflow_tpu.operators.runstore import RunStore
from kubeflow_tpu.webapps import JsonHandler

_EMBED_PAGE = """<!doctype html>
<html><head><title>{name} — kubeflow-tpu</title>
<style>body{{margin:0;font-family:sans-serif}}
nav{{padding:6px 12px;background:#f4f4f4;border-bottom:1px solid #ccc}}
iframe{{border:0;width:100vw;height:calc(100vh - 40px)}}</style></head>
<body><nav><a href="/">kubeflow-tpu</a> / {name}</nav>
<iframe src="{src}" title="{name}"></iframe>
</body></html>
"""

_PAGE = """<!doctype html>
<html><head><title>kubeflow-tpu</title>
<style>body{{font-family:sans-serif;margin:2rem}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:4px 10px}}</style></head>
<body><h1>kubeflow-tpu</h1>
<form method="get" action="/">Namespace:
<select name="namespace" onchange="this.form.submit()">{ns_options}</select>
<noscript><button type="submit">Go</button></noscript></form>
<h2>Components</h2><ul>{components}</ul>
<h2>Jobs</h2><table><tr><th>Kind</th><th>Name</th><th>Namespace</th>
<th>State</th></tr>{jobs}</table>
<h2>Notebooks</h2><table><tr><th>Name</th><th>Namespace</th><th>State</th>
</tr>{notebooks}</table>
<h2>Studies</h2><table><tr><th>Name</th><th>Namespace</th><th>State</th>
<th>Best</th></tr>{studies}</table>
<h2>Pipeline runs</h2><table><tr><th>Workflow</th><th>Schedule</th>
<th>Phase</th><th>Started</th><th>Finished</th><th>Artifacts</th></tr>
{runs}</table>
<h2>Activity</h2><table><tr><th>Time</th><th>Kind</th><th>Object</th>
<th>Event</th><th>Message</th></tr>{activity}</table>
</body></html>
"""


class Dashboard:
    def __init__(self, client: K8sClient, namespace: str | None = None):
        self.client = client
        self.namespace = namespace

    def _safe_list(self, api_version: str, kind: str,
                   namespace: str | None = None) -> list[dict]:
        try:
            return self.client.list(api_version, kind,
                                    namespace or self.namespace)
        except ApiError:
            return []

    def namespaces(self) -> list[str]:
        """Names for the namespace selector (reference:
        namespace-selector.js fed by /api/namespaces)."""
        try:
            return sorted(ns["metadata"]["name"]
                          for ns in self.client.list("v1", "Namespace"))
        except ApiError:
            return []

    def components(self, namespace: str | None = None) -> list[dict]:
        out = []
        for svc in self._safe_list("v1", "Service", namespace):
            for route in routes_from_service(svc):
                out.append({"name": route.name, "prefix": route.prefix,
                            "service": route.service})
        return out

    def _raw_jobs(self, namespace: str | None = None
                  ) -> list[tuple[str, dict]]:
        return [
            (kind, job)
            for kind in ALL_JOB_KINDS
            for job in self._safe_list(JOBS_API_VERSION, kind, namespace)
        ]

    def jobs(self, namespace: str | None = None,
             raw: list[tuple[str, dict]] | None = None) -> list[dict]:
        return [{
            "kind": kind,
            "name": job["metadata"]["name"],
            "namespace": job["metadata"]["namespace"],
            "state": job.get("status", {}).get("state", "Unknown"),
        } for kind, job in (raw if raw is not None
                            else self._raw_jobs(namespace))]

    def notebooks(self, namespace: str | None = None) -> list[dict]:
        return [{
            "name": nb["metadata"]["name"],
            "namespace": nb["metadata"]["namespace"],
            "state": nb.get("status", {}).get("state", "Unknown"),
        } for nb in self._safe_list(NOTEBOOKS_API_VERSION, NOTEBOOK_KIND,
                                    namespace)]

    def studies(self, namespace: str | None = None) -> list[dict]:
        return [{
            "name": s["metadata"]["name"],
            "namespace": s["metadata"]["namespace"],
            "state": s.get("status", {}).get("state", "Unknown"),
            "bestObjective": s.get("status", {}).get("bestObjective"),
        } for s in self._safe_list(TUNING_API_VERSION, STUDY_JOB_KIND,
                                   namespace)]

    def runs(self, namespace: str | None = None) -> list[dict]:
        """Workflow run history — outlives the Workflow CRs (RunStore,
        the pipeline-persistenceagent surface)."""
        try:
            return RunStore(self.client).list_runs(
                namespace or self.namespace)
        except ApiError:
            return []

    def activity(self, namespace: str | None = None, limit: int = 50,
                 raw_jobs: list[tuple[str, dict]] | None = None
                 ) -> list[dict]:
        """Recent state transitions harvested from object conditions —
        the dashboard-view.js activity feed, without a separate event
        store: every controller already timestamps its condition flips.
        ``raw_jobs`` lets overview() share one apiserver sweep between the
        job table and the feed."""
        events = []
        if raw_jobs is None:
            raw_jobs = self._raw_jobs(namespace)
        for kind, job in raw_jobs:
            m = job["metadata"]
            for cond in job.get("status", {}).get("conditions", []):
                if cond.get("status") != "True":
                    continue
                events.append({
                    "time": cond.get("lastTransitionTime", ""),
                    "kind": kind,
                    "name": m["name"],
                    "namespace": m["namespace"],
                    "event": cond.get("type", ""),
                    "message": cond.get("message", ""),
                })
        for wf in self._safe_list(PIPELINES_API_VERSION, WORKFLOW_KIND,
                                  namespace):
            m = wf["metadata"]
            status = wf.get("status", {})
            if status.get("phase"):
                events.append({
                    "time": status.get("finishedAt")
                    or status.get("startedAt", ""),
                    "kind": WORKFLOW_KIND,
                    "name": m["name"],
                    "namespace": m["namespace"],
                    "event": status["phase"],
                    "message": status.get("message", ""),
                })
        events.sort(key=lambda e: e["time"], reverse=True)
        return events[:limit]

    def overview(self, namespace: str | None = None) -> dict:
        raw_jobs = self._raw_jobs(namespace)
        return {
            "namespaces": self.namespaces(),
            "components": self.components(namespace),
            "jobs": self.jobs(raw=raw_jobs),
            "notebooks": self.notebooks(namespace),
            "studies": self.studies(namespace),
            "runs": self.runs(namespace),
            "activity": self.activity(namespace, raw_jobs=raw_jobs),
        }

    @staticmethod
    def _embeddable(prefix: str) -> bool:
        """Only same-origin path-shaped prefixes may become an
        auto-loading iframe src: the annotation is namespace-user-
        controlled, and a javascript: URI or protocol-relative
        //host (or \\-tricked) URL would load attacker content in the
        dashboard chrome on page load (html.escape cannot prevent it)."""
        return (prefix.startswith("/")
                and not prefix.startswith("//")
                and not prefix.startswith("/\\"))

    def render_embed(self, component: str) -> str | None:
        """In-place component view (centraldashboard's iframe-container
        pattern, public/components/iframe-container.js): the web app
        renders inside the dashboard chrome, reached through the gateway
        at its annotated prefix."""
        for c in self.components():
            if c["name"] == component and self._embeddable(c["prefix"]):
                return _EMBED_PAGE.format(name=html.escape(component),
                                          src=html.escape(c["prefix"]))
        return None

    def render_html(self, namespace: str | None = None) -> str:
        ov = self.overview(namespace)

        def esc(v) -> str:
            return html.escape(str(v))

        ns_options = "<option value=\"\">all namespaces</option>" + "".join(
            f"<option value=\"{esc(ns)}\""
            f"{' selected' if ns == namespace else ''}>{esc(ns)}</option>"
            for ns in ov["namespaces"]
        )
        def component_link(c) -> str:
            # Non-embeddable prefixes link straight to the component —
            # an /embed link would just 404 on the _embeddable guard.
            if not self._embeddable(c["prefix"]):
                return (f"<li><a href=\"{esc(c['prefix'])}\">"
                        f"{esc(c['name'])}</a> → {esc(c['service'])}</li>")
            return (f"<li><a href=\"/embed/{esc(quote(c['name'], safe=''))}"
                    f"\">{esc(c['name'])}</a> → {esc(c['service'])} "
                    f"(<a href=\"{esc(c['prefix'])}\">direct</a>)</li>")

        components = "".join(
            component_link(c) for c in ov["components"]
        ) or "<li>(none)</li>"
        jobs = "".join(
            f"<tr><td>{esc(j['kind'])}</td><td>{esc(j['name'])}</td>"
            f"<td>{esc(j['namespace'])}</td><td>{esc(j['state'])}</td></tr>"
            for j in ov["jobs"]
        )
        notebooks = "".join(
            f"<tr><td>{esc(n['name'])}</td><td>{esc(n['namespace'])}</td>"
            f"<td>{esc(n['state'])}</td></tr>" for n in ov["notebooks"]
        )
        studies = "".join(
            f"<tr><td>{esc(s['name'])}</td><td>{esc(s['namespace'])}</td>"
            f"<td>{esc(s['state'])}</td><td>{esc(s['bestObjective'])}</td>"
            "</tr>" for s in ov["studies"]
        )
        def _arts(r):
            return "; ".join(a["uri"] for a in r.get("artifacts", [])) \
                or "—"

        runs = "".join(
            f"<tr><td>{esc(r['workflow'])}</td>"
            f"<td>{esc(r.get('scheduledWorkflow', ''))}</td>"
            f"<td>{esc(r['phase'])}</td><td>{esc(r.get('startedAt', ''))}"
            f"</td><td>{esc(r.get('finishedAt', ''))}</td>"
            f"<td>{esc(_arts(r))}</td></tr>"
            for r in ov["runs"]
        )
        activity = "".join(
            f"<tr><td>{esc(e['time'])}</td><td>{esc(e['kind'])}</td>"
            f"<td>{esc(e['namespace'])}/{esc(e['name'])}</td>"
            f"<td>{esc(e['event'])}</td><td>{esc(e['message'])}</td></tr>"
            for e in ov["activity"]
        )
        return _PAGE.format(ns_options=ns_options, components=components,
                            jobs=jobs, notebooks=notebooks, studies=studies,
                            runs=runs, activity=activity)


def make_server(dash: Dashboard, port: int) -> ThreadingHTTPServer:
    class Handler(JsonHandler):
        def do_GET(self):
            url = urlsplit(self.path)
            ns = parse_qs(url.query).get("namespace", [None])[0] or None
            if url.path in ("/healthz", "/readyz"):
                self.send_json(200, {"status": "ok"})
            elif url.path == "/api/overview":
                self.send_json(200, dash.overview(ns))
            elif url.path == "/api/runs":
                self.send_json(200, {"runs": dash.runs(ns)})
            elif url.path == "/api/activity":
                self.send_json(200, {"activity": dash.activity(ns)})
            elif url.path == "/api/namespaces":
                self.send_json(200, {"namespaces": dash.namespaces()})
            elif url.path.startswith("/embed/"):
                page = dash.render_embed(unquote(url.path[len("/embed/"):]))
                if page is None:
                    self.send_json(404, {"error": "unknown component"})
                else:
                    self.send_html(200, page)
            elif url.path in ("/", "/index.html"):
                self.send_html(200, dash.render_html(ns))
            else:
                self.send_json(404, {"error": f"no route {url.path}"})

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)
