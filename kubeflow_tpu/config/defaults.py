"""Default component sets per platform.

The analogue of bootstrap/config/kfctl_default.yaml:5-40 (and the iap /
basic_auth variants): which components `kfctl init` puts in a fresh app.yaml
for each platform.
"""

from __future__ import annotations

from kubeflow_tpu.config.kfdef import (
    ComponentConfig,
    KfDef,
    KfDefSpec,
    PLATFORM_GCP_TPU,
    PLATFORM_NONE,
    TpuSpec,
)

# Core components every platform gets (the kfctl_default.yaml core list).
CORE_COMPONENTS = [
    "gateway",
    "centraldashboard",
    "training-operator",
    "training-dashboard",
    "notebook-controller",
    "jupyter-web-app",
    "profile-controller",
    "study-controller",
    "benchmark-operator",
    "metric-collector",
    "pipeline-operator",
    "application",
]

# Extra components for cloud deployments. cert-manager matches the
# reference's GCP variants always deploying certificate machinery
# (kfctl_gcp_iap-style configs); secure-ingress/cloud-endpoints stay
# opt-in because they need a real hostname parameter.
GCP_COMPONENTS = [
    "admission-webhook",
    "cert-manager",
]

# Deliberately optional (match reference opt-ins: spartakus, echo-server).
OPTIONAL_COMPONENTS = [
    "usage-reporter",
    "echo-server",
    "secure-ingress",
    "cloud-endpoints",
]


def default_components(platform: str) -> list[ComponentConfig]:
    names = list(CORE_COMPONENTS)
    if platform == PLATFORM_GCP_TPU:
        names += GCP_COMPONENTS
    return [ComponentConfig(name=n) for n in names]


def default_kfdef(
    name: str,
    platform: str = PLATFORM_NONE,
    namespace: str = "kubeflow",
    project: str = "",
    zone: str = "",
    accelerator: str = "v5litepod-8",
    topology: str = "2x4",
    num_slices: int = 1,
    use_basic_auth: bool = False,
) -> KfDef:
    spec = KfDefSpec(
        platform=platform,
        namespace=namespace,
        project=project,
        zone=zone,
        use_basic_auth=use_basic_auth,
        tpu=TpuSpec(
            accelerator=accelerator, topology=topology, num_slices=num_slices
        ),
        components=default_components(platform),
    )
    return KfDef(name=name, spec=spec)
