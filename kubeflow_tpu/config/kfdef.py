"""KfDef: the platform's typed application config.

The analogue of the reference's KfDef CRD schema
(bootstrap/pkg/apis/apps/kfdef/v1alpha1/application_types.go:24-92): an
app.yaml written by `kfctl init`, read by generate/apply/delete, describing the
target platform, the set of components to deploy, and per-component parameter
overrides. Where the reference layers ksonnet concepts (registries, packages,
prototypes, modules), here a *component* is simply a named entry in the
manifest package registry plus a param dict.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any

import yaml

from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE, __version__

KFDEF_API_VERSION = f"{API_GROUP}/v1"
KFDEF_KIND = "KfDef"

# Platforms the deployment engine knows how to drive (the analogue of the
# AllowedPlatforms list, bootstrap/pkg/apis/apps/group.go:38-56).
PLATFORM_NONE = "none"          # manifests only; user brings a cluster
PLATFORM_FAKE = "fake"          # in-process fake apiserver (tests / dry runs)
PLATFORM_MINIKUBE = "minikube"
PLATFORM_GCP_TPU = "gcp-tpu"    # GKE + TPU node pools / PodSlices
ALLOWED_PLATFORMS = (PLATFORM_NONE, PLATFORM_FAKE, PLATFORM_MINIKUBE, PLATFORM_GCP_TPU)


@dataclass
class Param:
    """One component parameter override (KsParameter analogue,
    application_types.go:77-81)."""

    name: str
    value: Any


@dataclass
class ComponentConfig:
    """A component to deploy: references a prototype in the manifest registry
    (KsComponent analogue, application_types.go:63-67)."""

    name: str                         # instance name (also default object name prefix)
    prototype: str | None = None      # registry prototype; defaults to `name`
    params: dict[str, Any] = field(default_factory=dict)
    # Kustomize-style overlay applied to the rendered objects (the v2
    # package-manager surface, kustomize.go:62-170); see
    # manifests.overlays.Overlay.from_dict for the accepted keys.
    overlay: dict[str, Any] = field(default_factory=dict)

    @property
    def prototype_name(self) -> str:
        return self.prototype or self.name


@dataclass
class TpuSpec:
    """TPU fleet description used by the gcp-tpu platform and the operators'
    topology allocator — the analogue of the GPU node-pool block in
    deployment/gke/deployment_manager_configs/cluster.jinja:132-158, recast
    for TPU slices."""

    accelerator: str = "v5litepod-8"   # TPU type / slice shape
    topology: str = "2x4"              # physical chip topology of the slice
    num_slices: int = 1                # multislice (DCN-connected) count
    runtime_version: str = "tpu-ubuntu2204-base"
    reserved: bool = False


@dataclass
class KfDefSpec:
    platform: str = PLATFORM_NONE
    namespace: str = DEFAULT_NAMESPACE
    app_dir: str = ""
    project: str = ""                  # cloud project (gcp-tpu)
    zone: str = ""
    email: str = ""
    ip_name: str = ""
    hostname: str = ""
    use_basic_auth: bool = False
    use_istio: bool = False
    enable_applications: bool = True
    delete_storage: bool = False
    tpu: TpuSpec = field(default_factory=TpuSpec)
    components: list[ComponentConfig] = field(default_factory=list)
    version: str = __version__

    def component(self, name: str) -> ComponentConfig | None:
        for c in self.components:
            if c.name == name:
                return c
        return None


@dataclass
class KfDef:
    name: str
    spec: KfDefSpec = field(default_factory=KfDefSpec)

    # ------------------------------------------------------------------
    # (de)serialisation
    # ------------------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "apiVersion": KFDEF_API_VERSION,
            "kind": KFDEF_KIND,
            "metadata": {"name": self.name, "namespace": self.spec.namespace},
            "spec": _spec_to_dict(self.spec),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "KfDef":
        if d.get("kind") != KFDEF_KIND:
            raise ValueError(f"not a KfDef: kind={d.get('kind')!r}")
        spec_d = dict(d.get("spec", {}))
        tpu_d = {_camel_to_snake(k): v for k, v in spec_d.pop("tpu", {}).items()}
        tpu_known = {f.name for f in dataclasses.fields(TpuSpec)}
        tpu_unknown = set(tpu_d) - tpu_known
        if tpu_unknown:
            raise ValueError(f"unknown KfDef tpu fields: {sorted(tpu_unknown)}")
        tpu = TpuSpec(**tpu_d)
        comps = [
            ComponentConfig(
                name=c["name"],
                prototype=c.get("prototype"),
                params=dict(c.get("params", {})),
                overlay=dict(c.get("overlay", {})),
            )
            for c in spec_d.pop("components", [])
        ]
        known = {f.name for f in dataclasses.fields(KfDefSpec)}
        fields = {_camel_to_snake(k): v for k, v in spec_d.items()}
        unknown = set(fields) - known
        if unknown:
            raise ValueError(f"unknown KfDef spec fields: {sorted(unknown)}")
        spec = KfDefSpec(tpu=tpu, components=comps, **fields)
        if spec.platform not in ALLOWED_PLATFORMS:
            raise ValueError(
                f"platform {spec.platform!r} not in {ALLOWED_PLATFORMS}"
            )
        return cls(name=d["metadata"]["name"], spec=spec)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            yaml.safe_dump(self.to_dict(), f, sort_keys=False)

    @classmethod
    def load(cls, path: str) -> "KfDef":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    @classmethod
    def load_app_dir(cls, app_dir: str) -> "KfDef":
        """Load the app.yaml in an app directory (coordinator.LoadKfApp
        analogue, bootstrap/pkg/kfapp/coordinator/coordinator.go:321)."""
        path = os.path.join(app_dir, "app.yaml")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no app.yaml in {app_dir}; run `kfctl init` first")
        kfdef = cls.load(path)
        kfdef.spec.app_dir = app_dir
        return kfdef


def _camel_to_snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _snake_to_camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _spec_to_dict(spec: KfDefSpec) -> dict:
    out: dict[str, Any] = {}
    for f in dataclasses.fields(spec):
        v = getattr(spec, f.name)
        if f.name == "tpu":
            out["tpu"] = {
                _snake_to_camel(k): val for k, val in dataclasses.asdict(v).items()
            }
        elif f.name == "components":
            out["components"] = [
                {
                    "name": c.name,
                    **({"prototype": c.prototype} if c.prototype else {}),
                    **({"params": c.params} if c.params else {}),
                    **({"overlay": c.overlay} if c.overlay else {}),
                }
                for c in v
            ]
        else:
            out[_snake_to_camel(f.name)] = v
    return out
