"""KfDef configuration types and platform defaults (kfctl's config surface)."""
from kubeflow_tpu.config.kfdef import KfDef, KfDefSpec, Param
from kubeflow_tpu.config import defaults

__all__ = ["KfDef", "KfDefSpec", "Param", "defaults"]
