"""Workflow artifact store: durable run outputs on a shared volume.

The KFP role filled by minio + mysql in the reference
(/root/reference/kubeflow/pipeline/minio.libsonnet:1-117 object store;
pipeline-persistenceagent.libsonnet:1-128 persistence): every workflow
task can declare outputs, the WorkflowController indexes them into the
durable run record, and later runs (or the dashboard) retrieve them by
URI. TPU-platform recast: the payload store is a PVC-backed directory
tree every task pod mounts (`nfs-volume`/`storage` package) — no minio
deployment to operate — while the run-record index stays in ConfigMaps
(:mod:`kubeflow_tpu.operators.runstore`). Both deliberately outlive the
Workflow CR.

Layout: ``<root>/<namespace>/<workflow>/<task>/<output-name>`` (a file or
a directory — checkpoints are directories). URIs are
``artifact://<namespace>/<workflow>/<task>/<name>``.

Task contract: the controller injects ``KUBEFLOW_ARTIFACT_DIR`` (this
run+task's output directory) and ``KUBEFLOW_ARTIFACT_ROOT`` into task
pods; a task writes its declared outputs under ``KUBEFLOW_ARTIFACT_DIR``
and downstream tasks resolve inputs with :func:`resolve` /
``python -m kubeflow_tpu.artifacts get``.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import asdict, dataclass

ENV_ROOT = "KUBEFLOW_ARTIFACT_ROOT"
ENV_DIR = "KUBEFLOW_ARTIFACT_DIR"
URI_SCHEME = "artifact://"
DEFAULT_ROOT = "/artifacts"


@dataclass(frozen=True)
class ArtifactRef:
    namespace: str
    workflow: str
    task: str
    name: str

    @property
    def uri(self) -> str:
        return (f"{URI_SCHEME}{self.namespace}/{self.workflow}/"
                f"{self.task}/{self.name}")


def _check_component(part: str) -> str:
    """Reject separators and dot-segments — every URI/name component maps
    to exactly one directory entry under the store root (path-traversal
    hardening: a Workflow author must not be able to read or write
    outside the store with the controller's privileges)."""
    if (not part or part in (".", "..") or "/" in part or "\\" in part
            or "\x00" in part):
        raise ValueError(f"invalid artifact path component {part!r}")
    return part


def parse_uri(uri: str) -> ArtifactRef:
    if not uri.startswith(URI_SCHEME):
        raise ValueError(f"not an artifact URI: {uri!r}")
    parts = uri[len(URI_SCHEME):].split("/")
    if len(parts) != 4:
        raise ValueError(
            f"artifact URI must be {URI_SCHEME}<ns>/<workflow>/<task>/"
            f"<name>: {uri!r}"
        )
    return ArtifactRef(*(_check_component(p) for p in parts))


class ArtifactStore:
    """File-backed store rooted at a shared (PVC) directory."""

    def __init__(self, root: str | None = None):
        self.root = root or os.environ.get(ENV_ROOT, DEFAULT_ROOT)

    # -- paths --------------------------------------------------------------

    def task_dir(self, namespace: str, workflow: str, task: str) -> str:
        for part in (namespace, workflow, task):
            _check_component(part)
        return os.path.join(self.root, namespace, workflow, task)

    def path_of(self, ref: ArtifactRef) -> str:
        return os.path.join(
            self.task_dir(ref.namespace, ref.workflow, ref.task),
            _check_component(ref.name),
        )

    # -- write --------------------------------------------------------------

    def put(self, ref: ArtifactRef, source: str | bytes) -> str:
        """Store a file, directory (copied), or raw bytes; returns the
        URI."""
        dest = self.path_of(ref)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if isinstance(source, bytes):
            with open(dest, "wb") as f:
                f.write(source)
        elif os.path.isdir(source):
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(source, dest)
        else:
            shutil.copy2(source, dest)
        return ref.uri

    # -- read ---------------------------------------------------------------

    def exists(self, ref: ArtifactRef) -> bool:
        return os.path.exists(self.path_of(ref))

    def resolve(self, uri: str) -> str:
        """URI → local path on the shared volume (raises if absent)."""
        ref = parse_uri(uri)
        path = self.path_of(ref)
        if not os.path.exists(path):
            raise FileNotFoundError(f"artifact {uri} not found at {path}")
        return path

    def read_bytes(self, uri: str) -> bytes:
        with open(self.resolve(uri), "rb") as f:
            return f.read()

    # -- index --------------------------------------------------------------

    def describe(self, ref: ArtifactRef) -> dict:
        path = self.path_of(ref)
        size = 0
        if os.path.isdir(path):
            for dirpath, _dirs, files in os.walk(path):
                size += sum(
                    os.path.getsize(os.path.join(dirpath, f))
                    for f in files
                )
            kind = "directory"
        else:
            size = os.path.getsize(path)
            kind = "file"
        return {**asdict(ref), "uri": ref.uri, "type": kind,
                "sizeBytes": size}

    def list_run(self, namespace: str, workflow: str) -> list[dict]:
        """Every artifact a run produced — keyed by run id (the workflow
        name), listable after the Workflow CR is gone (the payloads live
        on the volume, not under an ownerReference)."""
        run_dir = os.path.join(self.root, namespace, workflow)
        out = []
        if not os.path.isdir(run_dir):
            return out
        for task in sorted(os.listdir(run_dir)):
            task_dir = os.path.join(run_dir, task)
            if not os.path.isdir(task_dir):
                continue
            for name in sorted(os.listdir(task_dir)):
                out.append(self.describe(
                    ArtifactRef(namespace, workflow, task, name)))
        return out


def main(argv=None) -> int:
    """`python -m kubeflow_tpu.artifacts {put,get,list} ...` — the store
    CLI task containers use (the `mc`/minio-client analogue)."""
    import argparse
    import sys

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=None,
                   help=f"store root (default ${ENV_ROOT} or "
                        f"{DEFAULT_ROOT})")
    sub = p.add_subparsers(dest="cmd", required=True)
    put = sub.add_parser("put", help="store a file/directory as an artifact")
    put.add_argument("uri")
    put.add_argument("source")
    get = sub.add_parser("get", help="resolve an artifact URI to a path")
    get.add_argument("uri")
    lst = sub.add_parser("list", help="list a run's artifacts as JSON")
    lst.add_argument("namespace")
    lst.add_argument("workflow")
    args = p.parse_args(argv)
    store = ArtifactStore(args.root)
    if args.cmd == "put":
        print(store.put(parse_uri(args.uri), args.source))
    elif args.cmd == "get":
        print(store.resolve(args.uri))
    else:
        json.dump(store.list_run(args.namespace, args.workflow),
                  sys.stdout, indent=2)
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
