"""Token-corpus store: the real-data input pipeline.

A binary token file (format documented in native/tokenstore.cc) is
memory-mapped and sliced into training windows by the C++ library — batch
assembly is memcpy-speed with zero Python work per row. When the shared
library isn't built and no toolchain is available, a numpy fallback
implements the *identical* sampling arithmetic (same splitmix64 stream), so
batches are bit-identical across backends — asserted in tests.

Sampling is stateless in (seed, step): any step's batch can be recomputed
without replaying the stream, which is what makes checkpoint resume exact
(the train loop restarts at step N and the data stream follows).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator

import numpy as np

_MAGIC = 0x4B545055
_HEADER = np.dtype([
    ("magic", "<u4"), ("version", "<u4"), ("dtype", "<u4"), ("pad", "<u4"),
    ("n_tokens", "<u8"),
])

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtokenstore.so")

_lib = None
_lib_tried = False


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write an int32 token corpus in the KTPU binary format."""
    tokens = np.ascontiguousarray(tokens, dtype=np.int32).ravel()
    header = np.zeros((), _HEADER)
    header["magic"] = _MAGIC
    header["version"] = 1
    header["dtype"] = 4
    header["n_tokens"] = tokens.size
    with open(path, "wb") as f:
        f.write(header.tobytes())
        f.write(tokens.tobytes())


def _build_library() -> str | None:
    """Compile the C++ library in place (g++ is in the base toolchain);
    None when no compiler is available (numpy fallback takes over)."""
    src = os.path.join(_NATIVE_DIR, "tokenstore.cc")
    if os.path.exists(_LIB_PATH) and (
        os.path.getmtime(_LIB_PATH) >= os.path.getmtime(src)
    ):
        return _LIB_PATH
    # Compile to a per-process temp name and rename atomically: multi-host
    # launchers start every worker at once, and a CDLL of a half-written
    # .so from a sibling's in-flight g++ would kill that worker.
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-shared",
             src, "-o", tmp],
            check=True, capture_output=True, text=True, timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except (OSError, subprocess.SubprocessError):
        if os.path.exists(tmp):
            os.remove(tmp)
        return None


def _load_library():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    path = _build_library()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.ts_open.restype = ctypes.c_void_p
    lib.ts_open.argtypes = [ctypes.c_char_p]
    lib.ts_n_tokens.restype = ctypes.c_uint64
    lib.ts_n_tokens.argtypes = [ctypes.c_void_p]
    lib.ts_close.argtypes = [ctypes.c_void_p]
    lib.ts_fill_shuffled.restype = ctypes.c_int
    lib.ts_fill_shuffled.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.ts_fill_sequential.restype = ctypes.c_int
    lib.ts_fill_sequential.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint64,
    ]
    _lib = lib
    return _lib


def _splitmix64(x: int) -> int:
    mask = (1 << 64) - 1
    x = (x + 0x9E3779B97F4A7C15) & mask
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & mask
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & mask
    return x ^ (x >> 31)


class TokenStore:
    """Reader over a KTPU token file; native-backed when possible."""

    def __init__(self, path: str, *, native: bool | None = None):
        self.path = path
        lib = _load_library() if native in (None, True) else None
        if native is True and lib is None:
            raise RuntimeError("native tokenstore requested but unavailable")
        self._lib = lib
        self._handle = None
        if lib is not None:
            handle = lib.ts_open(path.encode())
            if not handle:
                raise ValueError(f"not a KTPU token file: {path}")
            self._handle = ctypes.c_void_p(handle)
            self.n_tokens = int(lib.ts_n_tokens(self._handle))
            self._tokens = None
        else:
            header = np.fromfile(path, dtype=_HEADER, count=1)
            if header.size != 1 or header["magic"][0] != _MAGIC:
                raise ValueError(f"not a KTPU token file: {path}")
            self.n_tokens = int(header["n_tokens"][0])
            self._tokens = np.memmap(path, dtype=np.int32, mode="r",
                                     offset=_HEADER.itemsize,
                                     shape=(self.n_tokens,))

    @property
    def native(self) -> bool:
        return self._handle is not None

    def close(self) -> None:
        """Idempotent; the train loop closes the store when the input
        pipeline shuts down (prefetcher exit, preemption, exception)."""
        if self._handle is not None:
            self._lib.ts_close(self._handle)
            self._handle = None

    def __enter__(self) -> "TokenStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------

    def sample_batch(self, batch: int, width: int, *, seed: int = 0,
                     step: int = 0) -> np.ndarray:
        """[batch, width] int32 shuffled windows, stateless in (seed, step)."""
        out = np.empty((batch, width), np.int32)
        if self._handle is not None:
            rc = self._lib.ts_fill_shuffled(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                batch, width, seed, step,
            )
            if rc != 0:
                raise ValueError(f"corpus shorter than window {width}")
            return out
        if self.n_tokens < width:
            raise ValueError(f"corpus shorter than window {width}")
        span = self.n_tokens - width + 1
        for r in range(batch):
            off = _splitmix64(seed ^ (step * batch + r)) % span
            out[r] = self._tokens[off:off + width]
        return out

    def sequential_batch(self, batch: int, width: int, *, start_row: int,
                         shard: int = 0, num_shards: int = 1) -> np.ndarray:
        """Contiguous windows, rows strided across shards (epoch reads)."""
        out = np.empty((batch, width), np.int32)
        if self._handle is not None:
            rc = self._lib.ts_fill_sequential(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                batch, width, start_row, shard, num_shards,
            )
            if rc != 0:
                raise ValueError("bad sequential read args")
            return out
        n_windows = self.n_tokens // width
        if n_windows == 0 or num_shards <= 0:
            raise ValueError("bad sequential read args")
        for r in range(batch):
            row = (start_row + r) * num_shards + shard
            off = (row % n_windows) * width
            out[r] = self._tokens[off:off + width]
        return out

    def stream(self, batch: int, seq_len: int, *, seed: int = 0,
               start_step: int = 0, shard: int = 0,
               num_shards: int = 1) -> Iterator[dict]:
        """Training batches {"tokens": [batch, seq_len+1]}; each process
        perturbs the seed by its shard id so shards draw disjoint streams.

        Reads are stateless over a read-only mmap (native and numpy
        backends alike), so the iterator is safe to drive from the
        prefetcher's producer thread while the main thread steps."""
        step = start_step
        shard_seed = seed ^ _splitmix64(shard * 0x1000 + num_shards)
        while True:
            yield {"tokens": self.sample_batch(
                batch, seq_len + 1, seed=shard_seed, step=step)}
            step += 1
