"""Training runtime: the workload the reference's TFJob pods ran, TPU-native.

In the reference, training is a container image the operator launches
(tf_cnn_benchmarks via launcher.py); checkpoint/resume is delegated to the
workload (SURVEY.md §5.4). Here it's part of the framework:

- :mod:`~kubeflow_tpu.train.trainer` — SPMD train step factory: one jitted
  function over the mesh, donated state, grad clipping, metrics.
- :mod:`~kubeflow_tpu.train.optimizers` — optax optimizer + schedule presets.
- :mod:`~kubeflow_tpu.train.checkpoint` — orbax save/restore (restart-from-
  checkpoint, which the reference lacks entirely).
- :mod:`~kubeflow_tpu.train.data` — synthetic + host-sharded batch pipelines.
- :mod:`~kubeflow_tpu.train.prefetch` — overlapped input pipeline (background
  producer placing batch N+k while step N runs).
- :mod:`~kubeflow_tpu.train.loop` — the worker entrypoint JaxJob pods run.
"""

from kubeflow_tpu.train.prefetch import Prefetcher
from kubeflow_tpu.train.trainer import TrainState, build_train_step, init_state

__all__ = ["Prefetcher", "TrainState", "build_train_step", "init_state"]
