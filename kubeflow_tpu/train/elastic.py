"""Elastic training: scheduler-driven live resharding (Tenplex-style).

The glue between the cluster scheduler's placement annotation and the
training loop's step boundary. The scheduler resizes an elastic job by
rewriting ``kubeflow-tpu.org/placement`` (grant inside
``spec.elastic.{minReplicas,maxReplicas}``); the loop polls that
annotation between steps and, on a changed target, drains the input
pipeline, remaps the live TrainState onto the new mesh
(:mod:`kubeflow_tpu.parallel.reshard` — bit-for-bit, device-to-device
with a host-gather fallback), rebuilds the jitted step, re-anchors the
data stream (stateless in ``(seed, step)``) and continues — no process
restart, no lost step.

Byte-equality contract: the resharded continuation is bitwise identical
to stopping at the reshard step and restoring the checkpoint into the
target mesh (the rescale path this replaces). Compute across different
mesh degrees is f32-equivalent but NOT bitwise to a fixed-mesh run (psum
partial grouping follows the shard count — the serving tp caveat class),
so that restore-path run IS the "undisturbed reference at the same
global batch" the tests and the chaos soak pin against.
"""

from __future__ import annotations

import logging
import os
from typing import Callable

import jax

from kubeflow_tpu.observability.metrics import MetricRegistry
from kubeflow_tpu.operators.base import OPERATOR_METRICS
from kubeflow_tpu.parallel.reshard import (
    ReshardStats,
    reshard_pytree,
    scaled_mesh_config,
)

log = logging.getLogger(__name__)

# Reshard observability rides the shared operator registry: in-process
# runs (tests, the manager's embedded workers) surface on the operator
# /metrics scrape; subprocess workers report the same numbers through
# the result dict's `reshards` timeline.
M_RESHARDS = OPERATOR_METRICS.counter(
    "train_reshards_total",
    "Live train-state remaps between mesh shapes, by direction",
    labels=("direction",))
M_RESHARD_SECONDS = OPERATOR_METRICS.histogram(
    "train_reshard_seconds",
    "Wall time of one live reshard (drain to new jitted step ready)")

ENV_APISERVER = "KUBEFLOW_TPU_APISERVER"

# Sentinel for "no target visible here" in the gang all-reduce: above
# any real device count, so min() over the gang only surfaces a target
# every process has seen.
_NO_TARGET = 2**31 - 1


def placement_device_source(*, environ=None, client=None,
                            total_devices: int | None = None
                            ) -> Callable[[], int | None] | None:
    """A poll callable mapping the job's live placement annotation to a
    target device count, or None when the pod has no job identity (not
    operator-launched). Target devices = visible devices × granted/max —
    the pod is provisioned for the max grant, the mesh uses the granted
    fraction. Transient apiserver faults read as "no change": placement
    polling must never kill training."""
    from kubeflow_tpu.apis import jobs as jobs_api
    from kubeflow_tpu.apis import scheduling as sched_api

    env = os.environ if environ is None else environ
    name = env.get(jobs_api.ENV_JOB_NAME)
    if not name:
        return None
    ns = env.get(jobs_api.ENV_JOB_NAMESPACE, "default")
    kind = env.get(jobs_api.ENV_JOB_KIND, "JaxJob")
    if client is None:
        from kubeflow_tpu.k8s.client import (
            ClusterConfig,
            HttpK8sClient,
            KindRegistry,
        )

        # The default registry only maps builtins — teach it this job
        # kind's REST plural so the GET path resolves.
        registry = KindRegistry()
        registry.register_crd(jobs_api.job_crd(kind))
        host = env.get(ENV_APISERVER)
        client = HttpK8sClient(
            ClusterConfig(host=host) if host else None, registry)

    def poll() -> int | None:
        try:
            job = client.get(jobs_api.JOBS_API_VERSION, kind, name, ns)
        except Exception:
            return None
        grant = sched_api.placement_grant(job)
        if grant is None:
            return None
        granted, cap = grant
        n = total_devices if total_devices else len(jax.devices())
        return max(1, (n * granted) // cap)

    return poll


def agreed_target(local: int | None, num_processes: int) -> int | None:
    """Gang-consistent resize target: every process must act on the SAME
    target at the SAME step, but each polls the annotation independently
    and may see a rewrite at different steps. All-reduce the locally
    observed target (min over the gang, absent = +inf): the reduced
    value is identical on every process, so the EARLIEST observer's
    target drives the whole gang in lockstep (the same
    earliest-signal-wins shape as the SIGTERM agreement; two rewrites
    racing resolve to the smaller — safer — grant until the next poll
    converges). Rides the coordination-service KV like global_any (no
    XLA dispatch); single-process is a local no-op."""
    if num_processes <= 1:
        return local
    from kubeflow_tpu.parallel.distributed import global_min_int

    agreed = global_min_int(local if local is not None else _NO_TARGET)
    return None if agreed >= _NO_TARGET else agreed


def reshard_train_state(state, model, opt_cfg, base_mesh_config,
                        target_devices: int, *, accum_steps: int = 1,
                        registry: MetricRegistry | None = None):
    """Remap a live TrainState onto ``target_devices`` and rebuild the
    jitted step against the new mesh. Returns ``(mesh, state, step_fn,
    stats)``. The data axis absorbs the resize
    (:func:`~kubeflow_tpu.parallel.reshard.scaled_mesh_config`); the
    remap itself is pure data movement, bitwise lossless."""
    from kubeflow_tpu.parallel.mesh import build_mesh
    from kubeflow_tpu.train.trainer import build_train_step, state_shardings

    import time

    devices = jax.devices()
    if target_devices > len(devices):
        raise ValueError(
            f"target {target_devices} devices but only {len(devices)} "
            "are visible to this process")
    t0 = time.perf_counter()
    mesh = build_mesh(scaled_mesh_config(base_mesh_config, target_devices),
                      devices=devices[:target_devices])
    abstract = jax.eval_shape(lambda: state)
    shardings = state_shardings(abstract, mesh, model)
    result = reshard_pytree(state, shardings)
    step_fn = build_train_step(model, opt_cfg, mesh,
                               accum_steps=accum_steps)
    stats: ReshardStats = result.stats
    stats.seconds = time.perf_counter() - t0
    M_RESHARDS.labels(stats.direction).inc()
    M_RESHARD_SECONDS.observe(stats.seconds)
    return mesh, result.tree, step_fn, stats
