"""SPMD train step.

One jitted function over the whole mesh: forward, backward, optimizer update.
GSPMD inserts every collective (gradient reductions over data/fsdp, activation
collectives over tensor/sequence) from the sharding annotations — there is no
hand-written gradient allreduce anywhere, which is exactly what replaces the
reference's PS/Horovod machinery (SURVEY.md §2.2). State is donated so
parameters and optimizer slots update in place in HBM.
"""

from __future__ import annotations

from typing import Any

import chex
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.registry import ModelSpec
from kubeflow_tpu.parallel.sharding import tree_shardings
from kubeflow_tpu.train.optimizers import OptimizerConfig, build as build_opt


@chex.dataclass
class TrainState:
    step: Any
    params: Any
    opt_state: Any


def state_shardings(state: TrainState, mesh: Mesh, model: ModelSpec):
    """Shardings for the whole TrainState in one pass: the model's path rules
    match the param pytree and, because rules are substring regexes, the same
    param subpaths inside optimizer slots (`opt_state/…/mu/layers/attn/wq`);
    scalars (step, counts, schedules) fall through to replicated P()."""
    rules = model.partition_rules(model.config)
    return tree_shardings(mesh, state, rules)


def init_state(
    key,
    model: ModelSpec,
    opt_cfg: OptimizerConfig,
    mesh: Mesh | None = None,
) -> TrainState:
    """Initialize params + optimizer state, sharded over ``mesh`` at creation
    (jitted init with out_shardings — weights are born distributed, no
    host-memory spike for large models)."""
    opt = build_opt(opt_cfg)

    def make_state():
        params = model.init(key, model.config)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt.init(params),
        )

    if mesh is None:
        return make_state()
    abstract = jax.eval_shape(make_state)
    shardings = state_shardings(abstract, mesh, model)
    return jax.jit(make_state, out_shardings=shardings)()


def build_train_step(model: ModelSpec, opt_cfg: OptimizerConfig,
                     mesh: Mesh | None = None, *, accum_steps: int = 1):
    """Returns jitted ``(state, batch) -> (state, metrics)`` with donated
    state.

    ``accum_steps > 1`` turns the step into gradient-accumulation
    microbatching: ``batch`` leaves carry a leading [accum_steps, ...]
    axis (data.stack_microbatches) and the step scans the microbatches,
    accumulating the MEAN gradient in the gradient dtype
    (``opt_cfg.grad_dtype`` or the param dtype) before ONE optimizer
    update — effective batch ``accum_steps × batch_size`` at the HBM
    footprint of a single microbatch. Averaging microbatch-mean grads
    equals the grad of the equivalent single large batch, so loss/grad
    parity holds to dtype tolerance (pinned in tests). The accumulator
    lives in the scan carry, which XLA updates in place (donated
    buffers), and composes with every mesh axis: the scan axis is
    replicated, each microbatch keeps the model's batch sharding.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    opt = build_opt(opt_cfg)

    def grads_of(params, batch):
        def loss_of(p):
            return model.loss_fn(p, batch, model.config, mesh=mesh)

        diff_params = params
        if opt_cfg.grad_dtype:
            gdt = jnp.dtype(opt_cfg.grad_dtype)
            diff_params = jax.tree.map(
                lambda p: p.astype(gdt)
                if jnp.issubdtype(p.dtype, jnp.floating) else p,
                params,
            )
        (loss, metrics), grads = jax.value_and_grad(loss_of, has_aux=True)(
            diff_params
        )
        return loss, dict(metrics), grads

    def apply_update(state, metrics, grads):
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        state_updates = metrics.pop("_state_updates", None)
        if state_updates is not None and model.update_state is not None:
            params = model.update_state(params, state_updates)
        metrics["grad_norm"] = optax.global_norm(grads)
        metrics["step"] = state.step
        return (
            TrainState(step=state.step + 1, params=params,
                       opt_state=opt_state),
            metrics,
        )

    def step_fn(state: TrainState, batch):
        _, metrics, grads = grads_of(state.params, batch)
        return apply_update(state, metrics, grads)

    def accum_step_fn(state: TrainState, batch):
        def body(acc, microbatch):
            _, metrics, grads = grads_of(state.params, microbatch)
            # Divide per-microbatch: the accumulator holds a running
            # MEAN, so low-precision grad dtypes never see a k×-scaled
            # partial sum.
            acc = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype) / accum_steps,
                acc, grads)
            return acc, metrics

        zeros = jax.tree.map(
            lambda p: jnp.zeros(
                p.shape,
                jnp.dtype(opt_cfg.grad_dtype)
                if opt_cfg.grad_dtype and jnp.issubdtype(p.dtype,
                                                         jnp.floating)
                else p.dtype),
            state.params)
        grads, metrics = jax.lax.scan(body, zeros, batch)
        state_updates = metrics.pop("_state_updates", None)
        # Scalar metrics average over microbatches (mean loss over the
        # effective batch = mean of equal-size microbatch means); the
        # non-gradient state channel keeps the LAST microbatch's updates,
        # matching the trajectory of sequential small steps.
        metrics = {k: jnp.mean(v, axis=0) for k, v in metrics.items()}
        if state_updates is not None:
            metrics["_state_updates"] = jax.tree.map(
                lambda x: x[-1], state_updates)
        return apply_update(state, metrics, grads)

    fn = accum_step_fn if accum_steps > 1 else step_fn
    if mesh is None:
        return jax.jit(fn, donate_argnums=0)

    batch_spec = model.batch_partition_spec(model.config)
    lead = (None,) if accum_steps > 1 else ()

    def sharded_step(state, batch):
        # Truncate the spec to each leaf's rank: a rank-4 image spec must
        # not be applied to the rank-1 labels riding the same batch. The
        # accumulation scan axis (leading dim) stays replicated.
        def leaf_sharding(x):
            spec = lead + tuple(batch_spec)[: x.ndim - len(lead)]
            spec += (None,) * (x.ndim - len(spec))
            return NamedSharding(mesh, P(*spec))

        batch = jax.lax.with_sharding_constraint(
            batch, jax.tree.map(leaf_sharding, batch),
        )
        return fn(state, batch)

    return jax.jit(sharded_step, donate_argnums=0)
