"""Overlapped training input pipeline.

The synchronous loop pays host batch synthesis/TokenStore reads AND the
host→device transfer (``place_batch``) between every step — pure input
stall that "Exploring the limits of Concurrency in ML Training on Google
TPUs" identifies as a dominant non-compute MFU loss. :class:`Prefetcher`
moves that work onto a producer thread: while step N's dispatched
computation runs, the producer synthesizes batch N+k and places it on
device, so the consumer's ``next()`` usually finds a device-resident
batch already waiting.

Contracts the overlap must not break (all pinned in tests):

- **Order/byte identity.** A single producer pulls the wrapped stream
  in order; the consumer sees exactly the synchronous sequence —
  data-exact resume stays stateless in ``(seed, step)``.
- **Multi-host safety.** Each process wraps its OWN sharded stream and
  places only its local shard (``place_batch`` assembles the global
  array from process-local data); the producer thread never enters a
  cross-process collective.
- **Clean shutdown.** ``close()`` stops the producer even when it is
  blocked on a full queue (loop exit, preemption, exception); a
  producer-side exception surfaces on the consumer's next ``next()``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator

_DONE = object()  # stream exhausted


class Prefetcher:
    """Bounded background producer over a host-batch iterator.

    ``depth`` bounds host+device memory: at most ``depth`` placed
    batches wait in the queue (plus one in the producer's hands).
    ``host_wait_s`` accumulates consumer time blocked on the queue —
    the residual input stall the overlap could not hide.
    """

    def __init__(self, stream: Iterator, place: Callable | None, *,
                 depth: int = 2, name: str = "prefetch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self.host_wait_s = 0.0
        self.batches = 0
        self._stream = stream
        self._place = place
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, name=name, daemon=True)
        self._thread.start()

    # -- producer side --------------------------------------------------

    def _produce(self) -> None:
        try:
            for batch in self._stream:
                if self._place is not None:
                    batch = self._place(batch)
                if not self._put(batch):
                    return  # closed while we were blocked on a full queue
            self._put(_DONE)
        except BaseException as e:  # re-raised on the consumer side
            self._put(e)

    def _put(self, item) -> bool:
        """Enqueue, polling the stop flag so close() always unblocks."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer side --------------------------------------------------

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self):
        t0 = time.perf_counter()
        while True:
            try:
                item = self._queue.get(timeout=0.1)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    raise RuntimeError(
                        "prefetch producer died without a result")
        self.host_wait_s += time.perf_counter() - t0
        if item is _DONE:
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            raise item
        self.batches += 1
        return item

    def qsize(self) -> int:
        """Batches ready right now (observability; racy by nature)."""
        return self._queue.qsize()

    # -- lifecycle ------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        """Stop the producer and join it. Idempotent; safe mid-stream
        (preemption), after exhaustion, and after a consumer exception."""
        self._stop.set()
        # Drain so a producer blocked on put() observes the stop promptly.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
