"""Training loop — the entrypoint JaxJob worker pods run.

The TPU-native analogue of the reference's launcher.py (tf-controller-
examples/tf-cnn/launcher.py): read the operator-injected rendezvous env, join
the collective, build the mesh, train with periodic checkpoint, report
throughput. Runs identically on one chip, the CPU fake slice, or a multi-host
TPU slice.
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field

import jax

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.parallel.distributed import initialize_from_env
from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
from kubeflow_tpu.train import checkpoint as ckpt_lib
from kubeflow_tpu.train.data import place_batch, synthetic_stream
from kubeflow_tpu.train.optimizers import OptimizerConfig
from kubeflow_tpu.train.trainer import (
    build_train_step,
    init_state,
    state_shardings,
)


@dataclass
class RunConfig:
    model: str = "lm-test-tiny"
    model_overrides: dict = field(default_factory=dict)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    batch_size: int = 8
    seq_len: int = 128
    steps: int = 100
    log_every: int = 10
    # KTPU token-corpus file (train.tokenstore); empty = synthetic data.
    data_path: str | None = None
    checkpoint_dir: str | None = None
    checkpoint_every: int = 500
    seed: int = 0
    # jax.profiler trace capture (SURVEY §5.1 — the subsystem the reference
    # lacks): traces profile_steps steps starting at profile_start_step
    # (after compilation) into profile_dir, viewable in tensorboard/xprof
    # via the tensorboard manifest package.
    profile_dir: str | None = None
    profile_start_step: int = 3
    profile_steps: int = 5


def run(cfg: RunConfig, *, log=print) -> dict:
    """Train; returns final metrics {step, loss, samples_per_sec, ...}."""
    info = initialize_from_env()
    model = get_model(cfg.model, **cfg.model_overrides)
    # A multislice gang (MEGASCALE env) must get the hybrid DCN placement —
    # slices span the data axis; ICI-hungry axes stay within slices.
    mesh = build_mesh(
        cfg.mesh,
        num_slices=info.num_slices if info.is_multislice else None,
    )
    opt_cfg = cfg.optimizer

    state = init_state(jax.random.PRNGKey(cfg.seed), model, opt_cfg, mesh)
    start_step = 0
    if cfg.checkpoint_dir:
        abstract = jax.eval_shape(lambda: state)
        abstract = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, state_shardings(abstract, mesh, model),
        )
        restored = ckpt_lib.restore_latest(cfg.checkpoint_dir, abstract)
        if restored is not None:
            state, start_step = restored
            log(f"resumed from checkpoint step {start_step}")

    step_fn = build_train_step(model, opt_cfg, mesh)
    if cfg.data_path:
        from kubeflow_tpu.train.tokenstore import TokenStore

        # Stateless in (seed, step): restarting at start_step replays the
        # exact stream position — checkpoint resume is data-exact.
        stream = TokenStore(cfg.data_path).stream(
            cfg.batch_size, cfg.seq_len, seed=cfg.seed,
            start_step=start_step, shard=info.process_id,
            num_shards=info.num_processes,
        )
        if getattr(model.config, "context_parallel", False):
            # Sequence-sharded batches need seq divisible by the mesh axis:
            # ship the shifted pair, not the odd-length token array (same
            # convention as data.synthetic_batch).
            stream = (
                {"inputs": b["tokens"][:, :-1], "targets": b["tokens"][:, 1:]}
                for b in stream
            )
    else:
        stream = synthetic_stream(model, cfg.batch_size, cfg.seq_len,
                                  seed=cfg.seed, start_step=start_step)

    metrics = {}
    t_last = time.perf_counter()
    samples_since = 0
    throughput = 0.0
    profiling = False
    for step in range(start_step, cfg.steps):
        if cfg.profile_dir and info.process_id == 0:
            if step - start_step == cfg.profile_start_step:
                jax.profiler.start_trace(cfg.profile_dir)
                profiling = True
            elif (profiling and
                  step - start_step ==
                  cfg.profile_start_step + cfg.profile_steps):
                jax.profiler.stop_trace()
                profiling = False
                log(f"profiler trace written to {cfg.profile_dir}")
        batch = place_batch(next(stream), mesh, model)
        state, metrics = step_fn(state, batch)
        samples_since += cfg.batch_size
        if (step + 1) % cfg.log_every == 0 or step + 1 == cfg.steps:
            loss = float(metrics["loss"])  # sync point
            now = time.perf_counter()
            throughput = samples_since / (now - t_last)
            t_last, samples_since = now, 0
            log(
                f"step={step + 1} loss={loss:.4f} "
                f"samples/sec={throughput:.1f}"
            )
        if (
            cfg.checkpoint_dir
            and (step + 1) % cfg.checkpoint_every == 0
        ):
            ckpt_lib.save(cfg.checkpoint_dir, step + 1, state)
    if profiling:  # short runs: close the trace instead of dropping it
        jax.profiler.stop_trace()
        log(f"profiler trace written to {cfg.profile_dir}")
    if cfg.checkpoint_dir and ckpt_lib.latest_step(cfg.checkpoint_dir) != cfg.steps:
        ckpt_lib.save(cfg.checkpoint_dir, cfg.steps, state, force=True)

    result = {
        "step": cfg.steps,
        "loss": float(metrics["loss"]) if metrics else None,
        "samples_per_sec": throughput,
        "process_id": info.process_id,
    }
    if info.process_id == 0:
        publish_metrics(result, log=log)
    return result


def publish_metrics(result: dict, *, client=None, environ=None, log=print):
    """Publish final metrics into the owning job's status.metrics — the path
    the study/benchmark controllers read (the reference scrapes worker logs
    with a metricsCollector CronJob instead,
    kubeflow/katib/studyjobcontroller.libsonnet:115-147). Also emits the
    log-line form for log-scraping collectors."""
    import os

    from kubeflow_tpu.apis.jobs import (
        ENV_JOB_KIND,
        ENV_JOB_NAME,
        ENV_JOB_NAMESPACE,
        JOBS_API_VERSION,
    )

    env = os.environ if environ is None else environ
    metrics = {k: v for k, v in result.items()
               if isinstance(v, (int, float)) and v is not None}
    log(f"kubeflow-tpu-metrics: {json.dumps(metrics)}")
    name = env.get(ENV_JOB_NAME)
    if not name:
        return
    ns = env.get(ENV_JOB_NAMESPACE, "default")
    kind = env.get(ENV_JOB_KIND, "JaxJob")
    if client is None:
        from kubeflow_tpu.k8s.client import HttpK8sClient

        client = HttpK8sClient()
    try:
        job = client.get(JOBS_API_VERSION, kind, name, ns)
        job.setdefault("status", {})["metrics"] = metrics
        client.update_status(job)
    except Exception as e:  # metrics publishing must never kill training
        log(f"metrics publish failed: {e}")


def main(argv=None) -> int:
    """`python -m kubeflow_tpu.train.loop '<json run config>'`"""
    import os

    argv = sys.argv[1:] if argv is None else argv
    overrides = json.loads(argv[0]) if argv else {}
    mesh_cfg = MeshConfig(**overrides.pop("mesh", {}))
    opt_cfg = OptimizerConfig(**overrides.pop("optimizer", {}))
    # Path fields honor env references ($KUBEFLOW_ARTIFACT_DIR & co.),
    # so a workflow task can target its injected artifact directory
    # without knowing the store root at authoring time.
    for key in ("checkpoint_dir", "data_path", "profile_dir"):
        if overrides.get(key):
            overrides[key] = os.path.expandvars(overrides[key])
    cfg = RunConfig(mesh=mesh_cfg, optimizer=opt_cfg, **overrides)
    result = run(cfg)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
