"""Training loop — the entrypoint JaxJob worker pods run.

The TPU-native analogue of the reference's launcher.py (tf-controller-
examples/tf-cnn/launcher.py): read the operator-injected rendezvous env, join
the collective, build the mesh, train with periodic checkpoint, report
throughput. Runs identically on one chip, the CPU fake slice, or a multi-host
TPU slice.
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field

import jax

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.observability.metrics import Histogram
from kubeflow_tpu.parallel.distributed import global_any, initialize_from_env
from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
from kubeflow_tpu.train import checkpoint as ckpt_lib
from kubeflow_tpu.train.data import (
    place_batch,
    stack_microbatches,
    synthetic_stream,
)
from kubeflow_tpu.train.optimizers import OptimizerConfig
from kubeflow_tpu.train.prefetch import Prefetcher
from kubeflow_tpu.train.trainer import (
    build_train_step,
    init_state,
    state_shardings,
)


@dataclass
class RunConfig:
    model: str = "lm-test-tiny"
    model_overrides: dict = field(default_factory=dict)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    batch_size: int = 8
    seq_len: int = 128
    steps: int = 100
    log_every: int = 10
    # Input-pipeline overlap (train.prefetch): a producer thread
    # synthesizes/reads and places batch N+k while step N runs; `prefetch`
    # is the queue depth (0 = fully synchronous). Batch order is
    # byte-identical either way, so resume stays data-exact.
    prefetch: int = 2
    # Gradient accumulation (trainer.build_train_step): each optimizer
    # step scans `accum_steps` microbatches of `batch_size` rows —
    # effective batch batch_size×accum_steps at fixed HBM. The data
    # stream advances accum_steps microbatches per step.
    accum_steps: int = 1
    # Elastic resharding (train.elastic): poll the scheduler's placement
    # annotation every `elastic_poll_steps` steps; on a changed device
    # grant, drain the prefetcher, remap the live state onto the new
    # mesh (bit-for-bit), rebuild the jitted step and continue at the
    # same step — the data axis absorbs the resize, the global batch is
    # unchanged. 0 = fixed mesh.
    elastic_poll_steps: int = 0
    # KTPU token-corpus file (train.tokenstore); empty = synthetic data.
    data_path: str | None = None
    checkpoint_dir: str | None = None
    checkpoint_every: int = 500
    # Save asynchronously (orbax background commit) so checkpoint cadence
    # doesn't cost step time; the preemption/final save always waits.
    checkpoint_async: bool = True
    # Catch SIGTERM (the kubelet's eviction signal) and spend the grace
    # window saving a final checkpoint at the *eviction* step, so a
    # preempted run loses zero completed steps on resume (SURVEY §5.3).
    graceful_shutdown: bool = True
    seed: int = 0
    # jax.profiler trace capture (SURVEY §5.1 — the subsystem the reference
    # lacks): traces profile_steps steps starting at profile_start_step
    # (after compilation) into profile_dir, viewable in tensorboard/xprof
    # via the tensorboard manifest package.
    profile_dir: str | None = None
    profile_start_step: int = 3
    profile_steps: int = 5


def run(cfg: RunConfig, *, log=print, mesh_source=None) -> dict:
    """Train; returns final metrics {step, loss, samples_per_sec, ...}.

    ``mesh_source`` (tests/bench inject it; ``elastic_poll_steps`` builds
    the placement-annotation poller for operator-launched pods) is a
    zero-arg callable returning the current target device count, or None
    for "no signal" — the loop reshards at the next poll boundary when
    the gang-agreed target differs from the running mesh."""
    from kubeflow_tpu.train import elastic as elastic_lib

    info = initialize_from_env()
    model = get_model(cfg.model, **cfg.model_overrides)
    if mesh_source is None and cfg.elastic_poll_steps > 0:
        mesh_source = elastic_lib.placement_device_source()
    if mesh_source is not None and info.is_multislice:
        log("elastic resharding is single-slice only; ignoring the "
            "placement poller on this multislice gang")
        mesh_source = None
    # A multislice gang (MEGASCALE env) must get the hybrid DCN placement —
    # slices span the data axis; ICI-hungry axes stay within slices.
    if mesh_source is not None:
        # Elastic: the scheduler may have granted less than the max at
        # admission — the FIRST mesh already honors the grant.
        target = elastic_lib.agreed_target(mesh_source(),
                                           info.num_processes)
        n = min(target or len(jax.devices()), len(jax.devices()))
        try:
            mesh = build_mesh(
                elastic_lib.scaled_mesh_config(cfg.mesh, n),
                devices=jax.devices()[:n])
        except ValueError as e:
            log(f"ignoring initial elastic grant of {n} device(s): {e}")
            mesh = build_mesh(cfg.mesh)
    else:
        mesh = build_mesh(
            cfg.mesh,
            num_slices=info.num_slices if info.is_multislice else None,
        )
    opt_cfg = cfg.optimizer

    state = init_state(jax.random.PRNGKey(cfg.seed), model, opt_cfg, mesh)
    start_step = 0
    ckpt = None
    if cfg.checkpoint_dir:
        ckpt = ckpt_lib.Checkpointer(cfg.checkpoint_dir,
                                     async_saves=cfg.checkpoint_async)
        abstract = jax.eval_shape(lambda: state)
        abstract = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, state_shardings(abstract, mesh, model),
        )
        restored = ckpt.restore_latest(abstract)
        if restored is not None:
            state, start_step = restored
            log(f"resumed from checkpoint step {start_step}")

    # Graceful preemption: Kubernetes evictions deliver SIGTERM with a
    # grace period — spend it finishing the in-flight step and saving.
    # (Registration only works on the main thread; library callers
    # running in a worker thread keep the default disposition. The
    # previous handler is restored on exit so a finished run doesn't
    # leave the process ignoring SIGTERM.)
    stop_requested = []
    prev_handler = None
    if cfg.graceful_shutdown:
        import signal

        def _on_sigterm(_signum, _frame):
            stop_requested.append(True)

        try:
            prev_handler = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            prev_handler = None  # not the main thread

    try:
        return _train(cfg, info, model, mesh, opt_cfg, state, start_step,
                      ckpt, stop_requested, log, mesh_source=mesh_source)
    finally:
        if prev_handler is not None:
            import signal

            signal.signal(signal.SIGTERM, prev_handler)


def _make_batches(cfg, info, model, mesh, stream_step, store):
    """(batches, prefetcher) for one mesh + stream position. The stream
    is stateless in (seed, microbatch-step), so an elastic reshard
    re-anchors it here at the current position — the prefetched lookahead
    the drain discarded is re-synthesized against the NEW mesh, byte-
    identical batch order either way."""
    if store is not None:
        stream = store.stream(
            cfg.batch_size, cfg.seq_len, seed=cfg.seed,
            start_step=stream_step, shard=info.process_id,
            num_shards=info.num_processes,
        )
        if getattr(model.config, "context_parallel", False):
            # Sequence-sharded batches need seq divisible by the mesh axis:
            # ship the shifted pair, not the odd-length token array (same
            # convention as data.synthetic_batch).
            stream = (
                {"inputs": b["tokens"][:, :-1], "targets": b["tokens"][:, 1:]}
                for b in stream
            )
    else:
        stream = synthetic_stream(model, cfg.batch_size, cfg.seq_len,
                                  seed=cfg.seed, start_step=stream_step)
    if cfg.accum_steps > 1:
        stream = stack_microbatches(stream, cfg.accum_steps)

    def place(b):
        return place_batch(b, mesh, model,
                           microbatched=cfg.accum_steps > 1)

    if cfg.prefetch > 0:
        # Each process prefetches only its own shard (the stream above is
        # already per-process); placement is collective-free, so the
        # producer thread is multi-host safe.
        prefetcher = Prefetcher(stream, place, depth=cfg.prefetch)
        return prefetcher, prefetcher
    return (place(b) for b in stream), None


def _train(cfg, info, model, mesh, opt_cfg, state, start_step, ckpt,
           stop_requested, log, mesh_source=None):
    from kubeflow_tpu.train import elastic as elastic_lib

    step_fn = build_train_step(model, opt_cfg, mesh,
                               accum_steps=cfg.accum_steps)
    # The stream position counts MICROBATCHES: an accumulating run
    # resumed at optimizer step N replays from microbatch N×accum_steps —
    # data-exact resume stays stateless in (seed, step).
    store = None
    if cfg.data_path:
        from kubeflow_tpu.train.tokenstore import TokenStore

        # Stateless in (seed, step): restarting at start_step replays the
        # exact stream position — checkpoint resume is data-exact.
        store = TokenStore(cfg.data_path)
    batches, prefetcher = _make_batches(
        cfg, info, model, mesh, start_step * cfg.accum_steps, store)
    poll_steps = (cfg.elastic_poll_steps
                  or (1 if mesh_source is not None else 0))

    # SIGTERM lands per pod at different steps, but checkpoint save is a
    # collective — under a gang the local flag is all-reduced each step
    # so every process breaks (and saves) at the SAME step.
    gang = cfg.graceful_shutdown and info.num_processes > 1

    metrics = {}
    t_start = time.perf_counter()
    t_last = t_start
    samples_per_step = cfg.batch_size * cfg.accum_steps
    samples_since = 0
    throughput = 0.0
    host_wait_total = 0.0
    host_wait_since = 0.0
    step_time_ema = None
    # Step-time distribution riding the stall accounting: the EMA hides
    # stragglers; the histogram's p50/p99 expose them (the signal a gang
    # scheduler needs to spot a slow replica).
    step_hist = Histogram()
    steps_done = 0
    profiling = False
    preempted_at = None
    reshards = []
    rejected_target = None
    try:
        for step in range(start_step, cfg.steps):
            if (mesh_source is not None and poll_steps
                    and (step - start_step) % poll_steps == 0):
                # Reshard point: the gang-agreed grant decides; the poll
                # cadence is deterministic in step, so every process
                # enters the agreement the same number of times.
                target = elastic_lib.agreed_target(mesh_source(),
                                                   info.num_processes)
                if (target and target != mesh.devices.size
                        and target != rejected_target):
                    t_rs = time.perf_counter()
                    try:
                        elastic_lib.scaled_mesh_config(cfg.mesh, target)
                        if target > len(jax.devices()):
                            raise ValueError(
                                f"only {len(jax.devices())} device(s) "
                                "visible to this process")
                    except ValueError as e:
                        rejected_target = target
                        log(f"ignoring reshard target {target}: {e}")
                    else:
                        rejected_target = None
                        # Drain in-flight prefetch BEFORE touching the
                        # state: the lookahead was placed for the old
                        # mesh; the stream re-anchors at this step.
                        if prefetcher is not None:
                            prefetcher.close()
                        if ckpt is not None:
                            # Reshard-point checkpoint: crash safety
                            # across the remap, and the restore-into-
                            # target replay the byte-equality pin
                            # compares against.
                            ckpt.save(step, state, force=True)
                            ckpt.wait()
                        mesh, state, step_fn, stats = (
                            elastic_lib.reshard_train_state(
                                state, model, opt_cfg, cfg.mesh, target,
                                accum_steps=cfg.accum_steps))
                        batches, prefetcher = _make_batches(
                            cfg, info, model, mesh,
                            step * cfg.accum_steps, store)
                        event = stats.to_dict()
                        event["step"] = step
                        event["downtime_seconds"] = round(
                            time.perf_counter() - t_rs, 6)
                        reshards.append(event)
                        log(f"resharded {stats.direction} "
                            f"{stats.from_devices}->{stats.to_devices} "
                            f"devices at step {step} in "
                            f"{stats.seconds * 1e3:.0f}ms ({stats.method})")
            t_step = time.perf_counter()
            if cfg.profile_dir and info.process_id == 0:
                if step - start_step == cfg.profile_start_step:
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                elif (profiling and
                      step - start_step ==
                      cfg.profile_start_step + cfg.profile_steps):
                    jax.profiler.stop_trace()
                    profiling = False
                    log(f"profiler trace written to {cfg.profile_dir}")
            # Host wait: time this step spent blocked on input (queue
            # wait under prefetch; synthesis + placement when
            # synchronous) — the stall the overlap exists to hide.
            t_fetch = time.perf_counter()
            batch = next(batches)
            host_wait = time.perf_counter() - t_fetch
            host_wait_total += host_wait
            host_wait_since += host_wait
            state, metrics = step_fn(state, batch)
            steps_done += 1
            samples_since += samples_per_step
            step_time = time.perf_counter() - t_step
            step_hist.observe(step_time)
            step_time_ema = (step_time if step_time_ema is None
                             else 0.9 * step_time_ema + 0.1 * step_time)
            if (step + 1) % cfg.log_every == 0 or step + 1 == cfg.steps:
                loss = float(metrics["loss"])  # sync point
                now = time.perf_counter()
                window = now - t_last
                throughput = samples_since / window
                stall_pct = 100.0 * host_wait_since / max(window, 1e-9)
                depth = (f" qdepth={prefetcher.qsize()}"
                         if prefetcher is not None else "")
                t_last, samples_since, host_wait_since = now, 0, 0.0
                log(
                    f"step={step + 1} loss={loss:.4f} "
                    f"samples/sec={throughput:.1f} "
                    f"input_stall={stall_pct:.1f}%"
                    f"{depth}"
                )
            stop_now = bool(stop_requested)
            if gang:
                stop_now = global_any(stop_now)
            if stop_now:
                # Eviction: save the just-completed step SYNCHRONOUSLY
                # (the grace window is for exactly this) so resume
                # continues from here, not from the last periodic
                # checkpoint. Under a gang, stop_now is the all-reduced
                # flag, so the save below is entered by every process at
                # the same step.
                preempted_at = step + 1
                if ckpt is not None:
                    ckpt.save(preempted_at, state, force=True)
                    ckpt.wait()
                    log(f"preempted: checkpoint saved at step "
                        f"{preempted_at}")
                break
            if ckpt is not None and (step + 1) % cfg.checkpoint_every == 0:
                ckpt.save(step + 1, state)  # async: training continues
    finally:
        # Loop exit, preemption, or an exception anywhere above: the
        # producer thread must never outlive the loop.
        if prefetcher is not None:
            prefetcher.close()
        if store is not None:
            store.close()
    total_time = time.perf_counter() - t_start
    if profiling:  # short runs: close the trace instead of dropping it
        jax.profiler.stop_trace()
        log(f"profiler trace written to {cfg.profile_dir}")
    if ckpt is not None:
        if preempted_at is None and ckpt.latest_step() != cfg.steps:
            ckpt.save(cfg.steps, state, force=True)
        ckpt.close()  # waits for pending async commits

    final_step = preempted_at if preempted_at is not None else cfg.steps
    result = {
        "step": final_step,
        "loss": float(metrics["loss"]) if metrics else None,
        "samples_per_sec": throughput,
        "process_id": info.process_id,
        "preempted": preempted_at is not None,
        # Input-stall accounting: fraction of wall time the loop sat
        # blocked on input, mean per-step host wait, and the step-time
        # EMA — the numbers that make the overlap win gated, not
        # asserted (bench.py train_input_stall_pct).
        "input_stall_pct": round(
            100.0 * host_wait_total / max(total_time, 1e-9), 2),
        "host_wait_ms_per_step": round(
            1e3 * host_wait_total / max(steps_done, 1), 3),
        "step_time_ema_ms": round(1e3 * (step_time_ema or 0.0), 3),
        "step_time_p50_ms": round(1e3 * step_hist.quantile(0.5), 3),
        "step_time_p99_ms": round(1e3 * step_hist.quantile(0.99), 3),
        "prefetch_depth": cfg.prefetch,
        "accum_steps": cfg.accum_steps,
        # Elastic reshard timeline: one event per live remap (direction,
        # devices, remap seconds, full downtime incl. drain + stream
        # re-anchor) — the Timeline-style record dashboards and the
        # run_elastic bench read.
        "devices": int(mesh.devices.size),
        "reshard_count": len(reshards),
        "reshards": reshards,
    }
    if info.process_id == 0 and preempted_at is None:
        publish_metrics(result, log=log)
    return result


def publish_metrics(result: dict, *, client=None, environ=None, log=print):
    """Publish final metrics into the owning job's status.metrics — the path
    the study/benchmark controllers read (the reference scrapes worker logs
    with a metricsCollector CronJob instead,
    kubeflow/katib/studyjobcontroller.libsonnet:115-147). Also emits the
    log-line form for log-scraping collectors."""
    import os

    from kubeflow_tpu.apis.jobs import (
        ENV_JOB_KIND,
        ENV_JOB_NAME,
        ENV_JOB_NAMESPACE,
        JOBS_API_VERSION,
    )

    env = os.environ if environ is None else environ
    metrics = {k: v for k, v in result.items()
               if isinstance(v, (int, float)) and v is not None}
    log(f"kubeflow-tpu-metrics: {json.dumps(metrics)}")
    name = env.get(ENV_JOB_NAME)
    if not name:
        return
    ns = env.get(ENV_JOB_NAMESPACE, "default")
    kind = env.get(ENV_JOB_KIND, "JaxJob")
    if client is None:
        from kubeflow_tpu.k8s.client import HttpK8sClient

        client = HttpK8sClient()
    try:
        job = client.get(JOBS_API_VERSION, kind, name, ns)
        job.setdefault("status", {})["metrics"] = metrics
        client.update_status(job)
    except Exception as e:  # metrics publishing must never kill training
        log(f"metrics publish failed: {e}")


def main(argv=None) -> int:
    """`python -m kubeflow_tpu.train.loop '<json run config>'`"""
    import os

    argv = sys.argv[1:] if argv is None else argv
    overrides = json.loads(argv[0]) if argv else {}
    mesh_cfg = MeshConfig(**overrides.pop("mesh", {}))
    opt_cfg = OptimizerConfig(**overrides.pop("optimizer", {}))
    # Path fields honor env references ($KUBEFLOW_ARTIFACT_DIR & co.),
    # so a workflow task can target its injected artifact directory
    # without knowing the store root at authoring time.
    for key in ("checkpoint_dir", "data_path", "profile_dir"):
        if overrides.get(key):
            overrides[key] = os.path.expandvars(overrides[key])
    cfg = RunConfig(mesh=mesh_cfg, optimizer=opt_cfg, **overrides)
    result = run(cfg)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
