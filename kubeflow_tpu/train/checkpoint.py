"""Checkpoint / resume via orbax.

The reference delegates checkpointing entirely to workloads and cloud storage
(models read from GCS/S3/PVC — SURVEY.md §5.4); job restart just reruns the
container. Here restart-from-checkpoint is a framework capability: the train
loop saves sharded TrainState periodically (ASYNC — the device keeps
training while orbax commits in the background, so checkpoint cadence
doesn't trade against MFU) and a final synchronous save on preemption, and
resumes from the latest step found. Multi-host safe — every process
participates in the save (orbax handles the per-shard writes + atomic
commit)."""

from __future__ import annotations

import os
from typing import Any

import orbax.checkpoint as ocp


def _manager(ckpt_dir: str, max_to_keep: int = 3, *,
             async_saves: bool = False) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(ckpt_dir),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True,
            enable_async_checkpointing=async_saves,
        ),
    )


class Checkpointer:
    """One persistent manager for a training run.

    ``save`` returns as soon as the on-device state is snapshotted;
    serialization and the atomic commit run on orbax's background thread
    (enable_async_checkpointing). ``wait`` blocks until every pending
    save is durable — call it before exiting (and on the preemption
    path, where the final save must land inside the grace window).
    """

    def __init__(self, ckpt_dir: str, *, max_to_keep: int = 3,
                 async_saves: bool = True):
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        self._mgr = _manager(ckpt_dir, max_to_keep,
                             async_saves=async_saves)

    def save(self, step: int, state: Any, *, force: bool = False) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state),
                       force=force)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, abstract_state: Any
                       ) -> tuple[Any, int] | None:
        step = self._mgr.latest_step()
        if step is None:
            return None
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state))
        return state, step

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def save(ckpt_dir: str, step: int, state: Any, *, force: bool = False) -> None:
    mgr = _manager(ckpt_dir)
    mgr.save(step, args=ocp.args.StandardSave(state), force=force)
    mgr.wait_until_finished()
    mgr.close()


def latest_step(ckpt_dir: str) -> int | None:
    if not os.path.isdir(ckpt_dir):
        return None
    mgr = _manager(ckpt_dir)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(ckpt_dir: str, step: int, abstract_state: Any) -> Any:
    """Restore into the structure/shardings of ``abstract_state`` (build it
    with jax.eval_shape + shardings so restoring places shards directly on
    device)."""
    mgr = _manager(ckpt_dir)
    state = mgr.restore(step, args=ocp.args.StandardRestore(abstract_state))
    mgr.close()
    return state


def restore_latest(ckpt_dir: str, abstract_state: Any) -> tuple[Any, int] | None:
    step = latest_step(ckpt_dir)
    if step is None:
        return None
    return restore(ckpt_dir, step, abstract_state), step
