"""Optimizer and schedule presets (optax)."""

from __future__ import annotations

from dataclasses import dataclass

import optax


@dataclass(frozen=True)
class OptimizerConfig:
    name: str = "adamw"
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    grad_clip_norm: float = 1.0
    min_lr_ratio: float = 0.1
    momentum: float = 0.9  # sgd only
    # adamw/adam first-moment dtype; "bfloat16" halves that slot's HBM
    # (the second moment stays float32 for update accuracy).
    mu_dtype: str | None = None
    # Differentiate w.r.t. a bfloat16 view of the float32 master weights:
    # the gradient tree materializes at 2 bytes/param instead of 4. The
    # backward pass already flows in bf16 activations, so the only added
    # rounding is the final per-param accumulation — the standard trade
    # for fitting wider models on one chip (master weights stay fp32).
    grad_dtype: str | None = None


def schedule(cfg: OptimizerConfig):
    """Linear warmup → cosine decay to min_lr_ratio·peak (the LLM-training
    default)."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * cfg.min_lr_ratio,
    )


def build(cfg: OptimizerConfig) -> optax.GradientTransformation:
    lr = schedule(cfg)
    if cfg.name == "adamw":
        opt = optax.adamw(
            lr, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
            weight_decay=cfg.weight_decay, mu_dtype=cfg.mu_dtype,
        )
    elif cfg.name == "adam":
        opt = optax.adam(lr, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                         mu_dtype=cfg.mu_dtype)
    elif cfg.name == "adafactor":
        # Factored second moment — O(d) optimizer state instead of O(d²),
        # the standard memory-bound choice for big models on one chip.
        opt = optax.adafactor(lr, min_dim_size_to_factor=128)
    elif cfg.name == "sgd":
        opt = optax.sgd(lr, momentum=cfg.momentum)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    if cfg.grad_clip_norm:
        opt = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), opt)
    return opt
