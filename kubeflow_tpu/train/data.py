"""Data pipelines.

Synthetic generators per model family (the benchmark default — the role
tf_cnn_benchmarks' synthetic data plays in the reference's perf harness,
tf-controller-examples/tf-cnn/README.md), plus the host→mesh placement helper
for real multi-host input: each process feeds its local shard and
``jax.make_array_from_process_local_data`` assembles the global batch.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from kubeflow_tpu.models.registry import ModelSpec


def synthetic_batch(model: ModelSpec, batch_size: int, seq_len: int = 512,
                    seed: int = 0) -> dict:
    """One host-resident numpy batch matching the model family's loss_fn."""
    rng = np.random.default_rng(seed)
    cfg = model.config
    if model.family in ("transformer",):
        tokens = rng.integers(0, cfg.vocab_size, (batch_size, seq_len + 1),
                              dtype=np.int32)
        if getattr(cfg, "context_parallel", False):
            # Sequence-sharded batches need seq divisible by the mesh axis;
            # ship the shifted pair instead of the odd-length token array.
            return {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
        return {"tokens": tokens}
    if model.family == "bert":
        tokens = rng.integers(0, cfg.vocab_size, (batch_size, seq_len),
                              dtype=np.int32)
        mask = rng.random((batch_size, seq_len)) < 0.15
        labels = np.where(mask, tokens, -1).astype(np.int32)
        return {"tokens": tokens, "mlm_labels": labels}
    if model.family == "resnet":
        images = rng.standard_normal(
            (batch_size, cfg.image_size, cfg.image_size, 3), np.float32
        )
        labels = rng.integers(0, cfg.num_classes, (batch_size,), np.int32)
        return {"images": images, "labels": labels}
    raise ValueError(f"unknown model family {model.family}")


def synthetic_stream(model: ModelSpec, batch_size: int, seq_len: int = 512,
                     seed: int = 0, start_step: int = 0) -> Iterator[dict]:
    """Stateless in (seed, step): a run resumed at ``start_step`` replays
    the exact batches the uninterrupted run would have seen — the same
    data-exact-resume contract the token-store stream keeps."""
    step = start_step
    while True:
        yield synthetic_batch(model, batch_size, seq_len, seed=seed + step)
        step += 1


def place_batch(batch: dict, mesh: Mesh, model: ModelSpec, *,
                microbatched: bool = False) -> dict:
    """Place a (per-process) host batch onto the mesh with the model's batch
    sharding. Single-process: device_put; multi-host: assemble the global
    array from each process's local shard. ``microbatched`` marks leaves
    carrying a leading [accum_steps, ...] axis (stack_microbatches): the
    scan axis stays replicated and the batch spec shifts one dim right."""
    spec = model.batch_partition_spec(model.config)
    lead = (None,) if microbatched else ()

    def place(x):
        x = np.asarray(x)
        ndim_spec = lead + tuple(spec)[: x.ndim - len(lead)]
        ndim_spec += (None,) * max(0, x.ndim - len(ndim_spec))
        sharding = NamedSharding(mesh, jax.sharding.PartitionSpec(*ndim_spec))
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, batch)


def stack_microbatches(stream: Iterator[dict],
                       accum_steps: int) -> Iterator[dict]:
    """[accum_steps, batch, ...] stacked host batches — the unit the
    gradient-accumulation train step scans (trainer.build_train_step
    ``accum_steps``). Consumes ``accum_steps`` stream entries per yield,
    in order, so the stream stays stateless in (seed, microbatch-step)."""
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    while True:
        micro = [next(stream) for _ in range(accum_steps)]
        yield jax.tree.map(lambda *xs: np.stack(xs), *micro)
