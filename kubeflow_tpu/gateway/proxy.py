"""The data-path request handler: routing, auth enforcement, backend
picks, streamed relay, retries, shadow mirroring, and upgrade tunnels.

Factored from the gateway module so each concern stays reviewable; the
behavior contract is the 15-test gateway E2E suite, unchanged across the
split. ``make_proxy_handler(gw)`` builds the BaseHTTPRequestHandler class
bound to one :class:`kubeflow_tpu.gateway.Gateway`. Streamed relay
(chunked re-encoding, SSE-safe flushing) and the HTTP/1.1 Upgrade TCP
tunnel live on the handler itself.
"""

from __future__ import annotations

import hashlib
import json
import socket
import threading
import time
import urllib.parse
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler

from kubeflow_tpu.gateway.resilience import OutlierStats
from kubeflow_tpu.observability.tracing import (
    REQUEST_ID_HEADER,
    gen_request_id,
)
from kubeflow_tpu.serving.affinity import (
    prefix_affinity_key,
    rendezvous_order,
)

# Hop-by-hop headers never forwarded (RFC 7230 §6.1).
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailers", "transfer-encoding", "upgrade",
    "host", "content-length",
}


def prompt_tokens_for(body: bytes | None) -> list | None:
    """The prompt token list out of a predict payload's first instance,
    or None when the body isn't one. Never raises — unparseable traffic
    simply carries no prompt."""
    if not body:
        return None
    try:
        payload = json.loads(body)
        inst = (payload.get("instances") or [None])[0] \
            if isinstance(payload, dict) else None
        toks = inst.get("tokens") if isinstance(inst, dict) else None
        if isinstance(toks, list) and toks:
            return toks
    except (ValueError, TypeError, UnicodeDecodeError):
        pass
    return None


def _affinity_head_bound(width: int) -> int:
    """How much of a long body the gateway buffers to compute an
    affinity key: room for the JSON scaffolding plus the leading
    ``width`` tokens the key actually hashes (~16 bytes per decimal
    token id is generous). Everything past the head spills to the
    backend unbuffered."""
    return max(4096, 16 * int(width) + 1024)


def leading_tokens_for(head: bytes, width: int) -> list | None:
    """Leading prompt tokens out of a TRUNCATED predict-payload head.
    ``json.loads`` rejects a cut-off body, but the affinity key only
    hashes the first ``width`` tokens — scan the head for the first
    ``"tokens"`` array and collect the integers that fit, so a long
    prompt routes to the SAME affine replica a short one with the same
    prefix does. Returns None (digest fallback) when no leading token
    run can be recovered. Never raises."""
    try:
        text = head.decode("utf-8", "ignore")
        idx = text.find('"tokens"')
        if idx < 0:
            return None
        start = text.find("[", idx)
        if start < 0:
            return None
        toks: list = []
        num = ""
        for ch in text[start + 1:]:
            if ch in "-0123456789":
                num += ch
            elif ch in ", \t\r\n]":
                if num:
                    toks.append(int(num))
                    num = ""
                    if len(toks) >= max(int(width), 1):
                        break
                if ch == "]":
                    break
            else:
                # Nested arrays / non-integer tokens: the strict parser
                # wouldn't have produced a token list either — fall back
                # to the digest key.
                return None
        return toks or None
    except (ValueError, OverflowError):
        return None


class _SpilledBody:
    """File-like request body for long payloads: the buffered head
    replays first, then the remainder streams straight from the client
    socket. ``http.client`` reads it in blocks, so the gateway never
    holds more than the head in memory. The caller must forward an
    explicit Content-Length of ``total_len`` (a file-like body without
    one would be re-encoded chunked, which plain CL-only backends
    don't speak)."""

    def __init__(self, head: bytes, rfile, remaining: int):
        self._head = head
        self._rfile = rfile
        self._remaining = max(int(remaining), 0)
        self.total_len = len(head) + self._remaining

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            rest = (self._rfile.read(self._remaining)
                    if self._remaining else b"")
            out = self._head + rest
            self._head, self._remaining = b"", 0
            return out
        if self._head:
            out, self._head = self._head[:n], self._head[n:]
            return out
        if self._remaining <= 0:
            return b""
        data = self._rfile.read(min(n, self._remaining))
        self._remaining = self._remaining - len(data) if data else 0
        return data


def affinity_key_for(body: bytes | None, path: str, width: int) -> str:
    """Routing key for a prefix-affine route: the prompt's leading
    tokens when the body is a predict payload (requests sharing a
    prefix land on the same replica — the point), a digest of the raw
    body otherwise, the path for bodyless requests. Never raises —
    unparseable traffic still routes deterministically."""
    if body:
        toks = prompt_tokens_for(body)
        if toks is not None:
            return prefix_affinity_key(toks, width)
        return hashlib.blake2b(body[:1024], digest_size=8).hexdigest()
    return path


def make_proxy_handler(gw):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _respond(self, code: int, body: bytes,
                     headers: dict | None = None) -> None:
            self.send_response(code)
            rid = getattr(self, "_request_id", None)
            if rid:
                self.send_header(REQUEST_ID_HEADER, rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if headers is None or "Content-Type" not in headers:
                self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if self.command != "HEAD":  # RFC 7231: HEAD has no body
                self.wfile.write(body)

        def _body_too_large(self, length: int) -> bool:
            """413 on a declared body beyond ``gw.max_body_bytes`` —
            BEFORE reading a byte of it, so an oversized long-context
            prompt costs the gateway a header parse, not a buffer."""
            if gw.max_body_bytes and length > gw.max_body_bytes:
                gw.errors_total += 1
                gw.body_rejected_total += 1
                self._respond(413, json.dumps(
                    {"error": f"request body {length} bytes exceeds "
                              f"max_body_bytes {gw.max_body_bytes}"}
                ).encode())
                self.close_connection = True  # unread body desyncs
                return True
            return False

        def _handle(self):
            gw.requests_total += 1
            # Request id: preserved when the client sent one, generated
            # otherwise — echoed on every response this gateway writes
            # and forwarded to the upstream, so one id follows the
            # request through gateway → server → decoder.
            self._request_id = (self.headers.get(REQUEST_ID_HEADER)
                                or gen_request_id())
            if self.path == "/healthz":
                self._respond(200, b'{"status":"ok"}')
                return
            if self.path.startswith("/.well-known/acme-challenge/"):
                token = self.path.rsplit("/", 1)[1]
                body = (gw.challenge_lookup(token)
                        if gw.challenge_lookup else None)
                if body is None:
                    self._respond(404, b'{"error":"unknown challenge"}')
                else:
                    self._respond(200, body.encode(),
                                  {"Content-Type": "text/plain"})
                return
            route = gw.table.match(self.path)
            if route is None:
                gw.errors_total += 1
                self._respond(
                    404,
                    json.dumps({"error": f"no route for {self.path}"})
                    .encode(),
                )
                return
            self._identity = None
            if route.jwt == "required" and gw.jwt_verifier is None:
                # Fail CLOSED: an operator demanded token checks on
                # this route but the gateway has no verifier — a
                # misconfiguration must not silently serve open.
                gw.errors_total += 1
                self._respond(503, json.dumps(
                    {"error": "route requires jwt but the gateway "
                              "has no verifier configured"}).encode())
                return
            if gw.jwt_verifier is not None and route.jwt != "off":
                claims, reason = gw.jwt_verifier.check(
                    self.command, self.path, self.headers
                )
                if claims is None:
                    # Browser sessions may still pass through
                    # forward-auth when it is configured (IAP serves
                    # both logins and SA id-tokens) — unless the
                    # route pins jwt: "required", which accepts
                    # nothing but a valid bearer token.
                    session_ok = (route.jwt != "required"
                                  and gw.auth_url
                                  and gw._authorized(self))
                    if not session_ok:
                        self._respond(401, json.dumps(
                            {"error": "unauthorized", "reason": reason}
                        ).encode(), {
                            "WWW-Authenticate":
                                f'Bearer error="{reason}"',
                            "Content-Type": "application/json",
                        })
                        return
                elif claims.get("sub"):
                    self._identity = str(claims["sub"])
            elif not gw._authorized(self):
                self._respond(
                    401, json.dumps({"error": "unauthorized",
                                     "login": "/login"}).encode(),
                )
                return
            # Overload shedding (multi-tenant QoS routes): an over-rate
            # tenant — or a fully saturated upstream pool — answers 429
            # + Retry-After HERE, before any upstream work, so overload
            # degrades to fast, actionable backpressure instead of a
            # queue collapsing behind the gateway. The tenant is the
            # X-Tenant header, else the authenticated identity, else
            # one implicit tenant.
            if route.qos_active:
                tenant = (self.headers.get("X-Tenant")
                          or self._identity or "default")
                ok, retry_after = gw.qos_admit(route, tenant)
                if not ok:
                    gw.qos_shed_total += 1
                    self._respond(429, json.dumps(
                        {"error": f"tenant {tenant!r} over admission "
                                  f"rate"}).encode(),
                        {"Retry-After":
                         str(max(1, int(retry_after + 0.999)))})
                    self.close_connection = True  # unread body desyncs
                    return
                if route.pressure > 0 and route.backends:
                    healthy = gw.health.filter_healthy(
                        [b[0] for b in route.backends])
                    if healthy and all(gw.load.depth(s) >= route.pressure
                                       for s in healthy):
                        # Every healthy backend is at its in-flight
                        # bound: queuing more here only stretches every
                        # tenant's tail. Retry-After 1s — depth drains
                        # on token timescales, not bucket refills.
                        gw.qos_shed_total += 1
                        self._respond(429, json.dumps(
                            {"error": "upstream pool saturated"}
                        ).encode(), {"Retry-After": "1"})
                        self.close_connection = True
                        return
            # Prefix-affine and hash-split routes hash the request BODY
            # (the prompt's leading tokens), so it must be read before
            # the pick — the other strategies keep the lazy read in
            # _proxy_http.
            body = None
            affinity_key = None
            if (route.strategy in ("prefix-affine", "hash-split")
                    and not self._is_upgrade()):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    gw.errors_total += 1
                    self._respond(400, json.dumps(
                        {"error": "malformed Content-Length"}).encode())
                    self.close_connection = True
                    return
                if self._body_too_large(length):
                    return
                bound = _affinity_head_bound(route.affinity_tokens)
                if length > bound:
                    # Long-context payload: hash only a bounded head for
                    # the backend pick and spill the remainder to the
                    # relay unbuffered — a multi-megabyte prompt must
                    # not be buffered (or JSON-parsed) at the gateway.
                    head = self.rfile.read(bound)
                    toks = leading_tokens_for(head, route.affinity_tokens)
                    affinity_key = (
                        prefix_affinity_key(toks, route.affinity_tokens)
                        if toks is not None else
                        hashlib.blake2b(head[:1024],
                                        digest_size=8).hexdigest())
                    body = _SpilledBody(head, self.rfile,
                                        length - len(head))
                else:
                    body = self.rfile.read(length) if length else b""
                    affinity_key = affinity_key_for(
                        body, self.path, route.affinity_tokens)
            service = self._pick_backend(route, key=affinity_key)
            if (route.prefill_backends and affinity_key is not None
                    and isinstance(body, bytes)
                    and self.path.endswith(":predict")):
                # Disaggregated two-hop: have the affine prefill
                # backend compute the prompt KV and push it to the
                # decode backend picked above, THEN relay the predict
                # there — where it prefix-hits the imported blocks.
                self._prefill_hop(route, body, affinity_key, service)
            target = route.target_for(self.path, service)
            # Re-point at the resolved backend address.
            target = target.replace(service, gw.resolve(service), 1)
            parts = urllib.parse.urlsplit(target)
            backend_path = parts.path + (
                "?" + parts.query if parts.query else ""
            )
            if self._is_upgrade():
                self._tunnel(route, parts.hostname, parts.port,
                             backend_path)
                return
            self._proxy_http(route, parts.hostname, parts.port,
                             backend_path, service, body=body)

        def _pick_backend(self, route, exclude: str | None = None,
                          key: str | None = None) -> str:
            """Choose a backend with ejected upstreams filtered out of
            the pick set (weighted draws, bandit arms, AND the
            rendezvous member set — the health machinery is how dead
            replicas leave the hash ring); ``exclude`` additionally
            drops the backend a retry just failed on."""
            if not route.backends:
                return route.service  # nowhere else to go
            services = gw.health.filter_healthy(
                [b[0] for b in route.backends]
            )
            if exclude and len(services) > 1:
                services = [s for s in services if s != exclude]
            if route.strategy == "prefix-affine":
                # Rendezvous placement: order[0] is the affine replica
                # for this key; excluding a dead/ejected backend remaps
                # ONLY its keys (survivors keep their order). Spill to
                # the least-loaded backend when the affine replica is
                # over the in-flight pressure bound OR its KV pool is
                # fuller than kv_pressure (staleness-bounded scrape;
                # no signal = no KV opinion, never "empty") — locality
                # yields to a real hotspot, and only then.
                order = rendezvous_order(key or self.path, services)
                picked = order[0]
                over_depth = (route.pressure > 0
                              and gw.load.depth(picked) >= route.pressure)
                fill = None
                if not over_depth and route.kv_pressure > 0:
                    fill = gw.kv_fill.fill(picked, gw.resolve)
                over_kv = (fill is not None
                           and fill >= route.kv_pressure)
                spill_kind = None
                if (over_depth or over_kv) and len(order) > 1:
                    # Directory-aware spill (fleet KV economy): prefer a
                    # backend already advertising this prefix — its trie
                    # (or peer-importable tier) is warm, so the spilled
                    # request pays a tail prefill instead of a full one.
                    # The directory only changes WHICH backend takes the
                    # spill, never WHETHER it happens: every candidate
                    # still has to actually relieve the pressure that
                    # triggered it (guards below), or the key stays home.
                    spill = None
                    if key is not None:
                        spill = next(
                            (h for h in gw.kv_directory.holders(key)
                             if h in order[1:]), None)
                        if spill is not None:
                            spill_kind = "directory"
                    if spill is None:
                        spill = gw.load.least_loaded(order[1:])
                        spill_kind = "spill"
                    if spill is not None and over_depth and \
                            gw.load.depth(spill) >= gw.load.depth(picked):
                        spill = None  # everyone is at least as deep
                    if spill is not None and over_kv:
                        sf = gw.kv_fill.fill(spill, gw.resolve)
                        if sf is not None and sf >= fill:
                            spill = None  # no less-full pool to go to
                    if spill is not None:
                        picked = spill
                        gw.affine_spills += 1
                    else:
                        spill_kind = None
                if key is not None:
                    gw.note_affinity(route.name, spill_kind or "affine")
                    # The picked backend is about to prefill (and pool)
                    # this prefix — advertise it so the NEXT spill of
                    # the same key prefers this backend over cold ones.
                    gw.kv_directory.publish(key, picked, tier="route")
            elif route.strategy == "hash-split":
                # Progressive delivery: the key's stable hash picks a
                # VERSION group (so an affine prefix sees exactly one
                # model version for the whole rollout), rendezvous
                # picks the replica within the group. Pressure spill
                # stays INSIDE the group — spilling across versions
                # would serve a conversation two different models and
                # corrupt the canary's latency comparison. A group
                # whose members are all unhealthy falls back to the
                # full healthy pool: serving the wrong version beats
                # serving 502s.
                split = route.pick_split((key or self.path).encode())
                members = set(split[2]) if split else set()
                group = [s for s in services if s in members] or services
                order = rendezvous_order(key or self.path, group)
                picked = order[0]
                if (route.pressure > 0
                        and gw.load.depth(picked) >= route.pressure
                        and len(order) > 1):
                    spill = gw.load.least_loaded(order[1:])
                    if (spill is not None
                            and gw.load.depth(spill)
                            < gw.load.depth(picked)):
                        picked = spill
                        gw.affine_spills += 1
            elif route.strategy == "epsilon-greedy":
                picked = gw.bandit.pick(route, gw.rng, services)
            else:
                weights = {b[0]: b[1] for b in route.backends}
                draw = [weights[s] for s in services]
                if not any(draw):  # only zero-weight backends left
                    draw = [1.0] * len(services)
                picked = gw.rng.choices(services, weights=draw)[0]
            # Consume the half-open trial only on the backend that
            # actually takes the request.
            gw.health.begin_trial(picked)
            return picked

        def _prefill_hop(self, route, body, key, decode_service) -> None:
            """Hop 1 of the disaggregated relay: POST ``:prefill`` at
            the affine prefill backend with ``handoff_to`` naming the
            decode backend, so the KV payload travels server-to-server
            and never transits the gateway. Best-effort — any failure
            just means the decode backend prefills the prompt itself
            (degraded, never wrong), so errors are counted, never
            surfaced to the client."""
            toks = prompt_tokens_for(body)
            if toks is None:
                return  # not a generate payload: nothing to hand off
            healthy = gw.health.filter_healthy(
                [b[0] for b in route.prefill_backends])
            if not healthy:
                gw.handoff_failures += 1
                return
            prefill_svc = rendezvous_order(key, healthy)[0]
            target = route.target_for(self.path, prefill_svc)
            target = target.replace(prefill_svc,
                                    gw.resolve(prefill_svc), 1)
            parts = urllib.parse.urlsplit(target)
            hop_path = parts.path.replace(":predict", ":prefill")
            payload = json.dumps({
                "instances": [{"tokens": toks}],
                "handoff_to": decode_service,
            }).encode()
            try:
                conn = HTTPConnection(parts.hostname, parts.port,
                                      timeout=gw.upstream_timeout)
                try:
                    conn.request(
                        "POST", hop_path, body=payload,
                        headers={"Content-Type": "application/json",
                                 REQUEST_ID_HEADER: self._request_id})
                    resp = conn.getresponse()
                    out = json.loads(resp.read() or b"{}")
                finally:
                    conn.close()
                if resp.status == 200 and out.get("handoff"):
                    gw.handoffs_total += 1
                    gw.health.record_success(prefill_svc)
                else:
                    gw.handoff_failures += 1
                    if resp.status >= 500:
                        gw.health.record_failure(prefill_svc)
            except (OSError, ValueError):
                gw.handoff_failures += 1
                gw.health.record_failure(prefill_svc)

        def _is_upgrade(self) -> bool:
            conn_tokens = [
                t.strip().lower()
                for t in self.headers.get("Connection", "").split(",")
            ]
            return ("upgrade" in conn_tokens
                    and bool(self.headers.get("Upgrade")))

        # -- plain HTTP: streamed relay -----------------------------

        def _proxy_http(self, route, host, port, path, service=None,
                        is_retry=False, body=None):
            # On a retry the request body stream is already consumed —
            # only bodyless idempotent methods reach here retrying.
            # ``body`` is pre-read when the route strategy needed it for
            # the backend pick (prefix-affine hashes the prompt).
            if body is None and not is_retry:
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    # Malformed client header: answer 400 instead of
                    # dying with an uncaught traceback and a dropped
                    # connection.
                    gw.errors_total += 1
                    self._respond(400, json.dumps(
                        {"error": "malformed Content-Length"}).encode())
                    self.close_connection = True  # unread body desyncs
                    return
                if self._body_too_large(length):
                    return
                body = self.rfile.read(length) if length else None
            # Forwarded prefix and authenticated identity are
            # gateway-asserted — client-supplied copies must never
            # reach the backend (spoofing). The request id is gateway-
            # asserted too, but *preserves* the client's value.
            headers = {
                k: v for k, v in self.headers.items()
                if k.lower() not in _HOP_HEADERS
                and k.lower() not in ("x-forwarded-prefix",
                                      "x-auth-identity",
                                      "x-request-id")
            }
            headers["X-Forwarded-Prefix"] = route.prefix
            headers[REQUEST_ID_HEADER] = self._request_id
            # A spilled long body streams from the client socket; the
            # explicit Content-Length keeps http.client from falling
            # back to chunked re-encoding (which CL-only backends don't
            # speak). Body-inspection features below are skipped for it
            # — only the head ever existed in gateway memory.
            spilled = isinstance(body, _SpilledBody)
            if spilled:
                headers["Content-Length"] = str(body.total_len)
            if getattr(self, "_identity", None):
                # The x-goog-authenticated-user-email analogue.
                headers["X-Auth-Identity"] = self._identity
            version = (route.version_of(service)
                       if route.splits and service else "")
            if version and not is_retry:
                gw.version_requests.labels(route.name, version).inc()
            if route.shadow and not is_retry and not spilled:
                # Shadow sampling is decided by the same stable key the
                # split uses (different salt): a sampled-in prefix is
                # mirrored on every turn, so the candidate sees whole
                # conversations at shadow_fraction of the load.
                mkey = affinity_key_for(body, self.path,
                                        route.affinity_tokens)
                if route.mirror_sample(mkey.encode()):
                    self._mirror(route, path, body, dict(headers))
            tag_headers = {}
            if route.outlier_threshold > 0 and not is_retry \
                    and not spilled:
                value = OutlierStats.feature(body)
                if value is not None:
                    z, is_out = gw.outliers.score(
                        route.name, value,
                        window=route.outlier_window,
                        threshold=route.outlier_threshold,
                    )
                    tag_headers = {
                        "X-Outlier": "true" if is_out else "false",
                        "X-Outlier-Score": str(z),
                    }
            bandit = (route.strategy == "epsilon-greedy"
                      and service is not None)
            # Gateway-hop timeline (skipped on the retry re-entry — the
            # original request's timeline is still open upstack).
            tl = None if is_retry else gw.trace.start(self._request_id)
            if tl is not None:
                tl.event("received", route=route.name,
                         method=self.command, path=self.path)
            conn = HTTPConnection(host, port,
                                  timeout=gw.upstream_timeout)
            if service is not None:
                # Queue-depth accounting spans the WHOLE upstream
                # exchange (streamed relays included) — the pressure
                # signal prefix-affine spill decisions read.
                gw.load.acquire(service)
            try:
                t_up = time.perf_counter()
                try:
                    self._connect_upstream(conn)
                    conn.request(self.command, path, body=body,
                                 headers=headers)
                    resp = conn.getresponse()
                except OSError as e:
                    if tl is not None:
                        tl.event("upstream_failed",
                                 upstream=f"{host}:{port}")
                    if bandit:
                        gw.bandit.record(route.name, service, 0.0)
                    if service is not None:
                        gw.health.record_failure(service)
                    # Idempotent-GET retry: one shot at a DIFFERENT
                    # healthy backend, under the retry budget (a
                    # connect failure never duplicated a request).
                    if (self.command in ("GET", "HEAD")
                            and not is_retry
                            and route.backends
                            and service is not None
                            and gw._retry_allowed()):
                        retry_to = self._pick_backend(
                            route, exclude=service)
                        if retry_to != service:
                            gw.retries_total += 1
                            r_target = route.target_for(
                                self.path, retry_to)
                            r_target = r_target.replace(
                                retry_to, gw.resolve(retry_to), 1)
                            p = urllib.parse.urlsplit(r_target)
                            self._proxy_http(
                                route, p.hostname, p.port,
                                p.path + ("?" + p.query
                                          if p.query else ""),
                                retry_to, is_retry=True,
                            )
                            return
                    gw.errors_total += 1
                    self._respond(
                        502,
                        json.dumps(
                            {"error": f"upstream {host}:{port}: {e}"}
                        ).encode(),
                    )
                    return
                # Per-route upstream latency distribution (connect →
                # response headers): the autoscaler-facing signal.
                elapsed = time.perf_counter() - t_up
                gw.upstream_latency.labels(route.name).observe(elapsed)
                if version:
                    # Per-version distribution: the rollout gate's
                    # incumbent-vs-candidate comparison source.
                    gw.version_upstream_latency.labels(
                        route.name, version).observe(elapsed)
                if tl is not None:
                    tl.event("upstream_response", status=resp.status,
                             upstream=f"{host}:{port}")
                if bandit:
                    # Implicit reward: server errors are failures.
                    gw.bandit.record(route.name, service,
                                     0.0 if resp.status >= 500 else 1.0)
                if service is not None:
                    # Passive health observation: 5xx counts against
                    # the upstream; anything else closes its circuit.
                    if resp.status >= 500:
                        gw.health.record_failure(service)
                    else:
                        gw.health.record_success(service)
                self._relay_response(resp, tag_headers)
            finally:
                conn.close()
                if service is not None:
                    gw.load.release(service)
                if tl is not None:
                    tl.close()  # idempotent; covers the error returns too

        def _mirror(self, route, path, body, headers):
            """Fire-and-forget request mirror (seldon shadow/outlier
            surface): the shadow backend sees live traffic, its
            response is discarded, its failures never touch the
            client."""
            addr = gw.resolve(route.shadow)
            host, _, port_s = addr.partition(":")
            method = self.command
            headers["X-Shadow"] = "true"
            version = route.version_of(route.shadow) or "shadow"
            route_name = route.name

            def send():
                gw.shadow_total += 1
                gw.version_shadow_total.labels(route_name,
                                               version).inc()
                t0 = time.perf_counter()
                try:
                    conn = HTTPConnection(
                        host, int(port_s or 80),
                        timeout=gw.upstream_timeout,
                    )
                    conn.request(method, path, body=body,
                                 headers=headers)
                    conn.getresponse().read()
                    conn.close()
                    # Response discarded; its LATENCY is the point —
                    # the candidate's distribution under live load,
                    # before it takes a single user-visible request.
                    gw.version_upstream_latency.labels(
                        route_name, version).observe(
                        time.perf_counter() - t0)
                except (OSError, ValueError):
                    pass

            threading.Thread(target=send, daemon=True).start()

        def _connect_upstream(self, conn):
            """Connect with one retry — connect-phase only, so an
            in-flight request is never duplicated against a slow but
            alive upstream (ksonnet.go:147-168's retry role at the
            connection level)."""
            try:
                conn.connect()
            except OSError:
                conn.close()
                time.sleep(0.1)
                conn.connect()

        def _relay_response(self, resp, extra_headers=None):
            try:
                # Parse the upstream length BEFORE the status line goes
                # out: a malformed upstream Content-Length must become a
                # clean 502, which is impossible once bytes are written.
                upstream_len = resp.getheader("Content-Length")
                if upstream_len is not None:
                    try:
                        upstream_len = int(upstream_len)
                    except ValueError:
                        gw.errors_total += 1
                        self._respond(502, json.dumps(
                            {"error": "malformed upstream Content-Length"}
                        ).encode())
                        return
                self.send_response(resp.status)
                for k, v in resp.getheaders():
                    # The request id on the wire is gateway-asserted
                    # (same value the upstream echoed) — drop the
                    # upstream copy so the client never sees it twice.
                    if (k.lower() not in _HOP_HEADERS
                            and k.lower() != "x-request-id"):
                        self.send_header(k, v)
                rid = getattr(self, "_request_id", None)
                if rid:
                    self.send_header(REQUEST_ID_HEADER, rid)
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                bodyless = (self.command == "HEAD"
                            or resp.status in (204, 304)
                            or 100 <= resp.status < 200)
                if bodyless or upstream_len is not None:
                    if upstream_len is not None:
                        self.send_header("Content-Length",
                                         str(upstream_len))
                    self.end_headers()
                    if not bodyless:
                        self._relay_known_length(resp, upstream_len)
                else:
                    self._relay_stream(resp)
                self.wfile.flush()
            except OSError:
                # Mid-stream failure: the status line is already gone;
                # drop the connection rather than corrupt the body.
                gw.errors_total += 1
                self.close_connection = True

        def _relay_known_length(self, resp, remaining: int) -> None:
            while remaining > 0:
                data = resp.read(min(65536, remaining))
                if not data:
                    # Upstream died short of its advertised length;
                    # the client was promised more bytes — drop the
                    # connection so it can't desync on a reuse.
                    gw.errors_total += 1
                    self.close_connection = True
                    return
                self.wfile.write(data)
                remaining -= len(data)

        def _relay_stream(self, resp) -> None:
            """Unknown upstream length (chunked/EOF-delimited):
            re-chunk and flush as data arrives so streaming bodies
            (SSE, token streams) are never buffered. HTTP/1.0 clients
            can't parse chunked — stream raw and close."""
            chunked = self.request_version != "HTTP/1.0"
            if chunked:
                self.send_header("Transfer-Encoding", "chunked")
            else:
                self.close_connection = True
            self.end_headers()
            while True:
                data = resp.read1(65536)
                if not data:
                    break
                if chunked:
                    self.wfile.write(
                        f"{len(data):x}\r\n".encode() + data + b"\r\n"
                    )
                else:
                    self.wfile.write(data)
                self.wfile.flush()
            if chunked:
                self.wfile.write(b"0\r\n\r\n")

        # -- HTTP/1.1 Upgrade: transparent TCP tunnel ---------------

        def _tunnel(self, route, host, port, path):
            """Forward the Upgrade handshake verbatim and then pump
            bytes both ways — the websocket path notebooks need
            (jupyter.libsonnet:97-106). The gateway never parses
            frames; after the handshake it is a plain TCP relay, so
            the backend's 101 (or its refusal) reaches the client
            unmodified."""
            try:
                backend = socket.create_connection(
                    (host, port), timeout=gw.upstream_timeout
                )
            except OSError as e:
                gw.errors_total += 1
                self._respond(
                    502,
                    json.dumps(
                        {"error": f"upstream {host}:{port}: {e}"}
                    ).encode(),
                )
                return
            gw.tunnels_total += 1
            lines = [f"{self.command} {path} HTTP/1.1",
                     f"Host: {host}:{port}",
                     f"X-Forwarded-Prefix: {route.prefix}",
                     f"{REQUEST_ID_HEADER}: {self._request_id}"]
            if getattr(self, "_identity", None):
                lines.append(f"X-Auth-Identity: {self._identity}")
            # Hop-by-hop headers are the handshake here — forward
            # everything except Host (rewritten above) and the
            # gateway-asserted headers (spoofing).
            lines += [
                f"{k}: {v}" for k, v in self.headers.items()
                if k.lower() not in ("host", "x-forwarded-prefix",
                                     "x-auth-identity", "x-request-id")
            ]
            try:
                backend.sendall(
                    ("\r\n".join(lines) + "\r\n\r\n").encode()
                )
                # Tunnel sockets outlive the 60s request timeout.
                backend.settimeout(None)
                self.connection.settimeout(None)
                done = threading.Event()

                def pump(read, write):
                    try:
                        while True:
                            data = read(65536)
                            if not data:
                                break
                            write(data)
                    except (OSError, ValueError):
                        pass
                    finally:
                        done.set()

                def write_client(data):
                    self.wfile.write(data)
                    self.wfile.flush()

                for read, write in (
                    (self.rfile.read1, backend.sendall),
                    (backend.recv, write_client),
                ):
                    threading.Thread(target=pump, args=(read, write),
                                     daemon=True).start()
                # First direction to close ends the tunnel; the
                # shutdown below unblocks the other pump.
                done.wait()
            finally:
                for s in (backend, self.connection):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                backend.close()
                self.close_connection = True

        do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _handle
        do_HEAD = do_OPTIONS = _handle

    return Handler

