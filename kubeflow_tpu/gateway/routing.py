"""Route model + annotation-discovered route table.

The ambassador mapping layer (kubeflow/common/ambassador.libsonnet:7-226):
every platform Service that wants routing carries a
`kubeflow-tpu.org/gateway-route` annotation (the `getambassador.io/config`
pattern — route spec {name, prefix, service, rewrite}); the gateway
watches Services and keeps a longest-prefix route table.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass

import yaml

from kubeflow_tpu.k8s.client import K8sClient
from kubeflow_tpu.manifests.core import GATEWAY_ROUTE_ANNOTATION

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Route:
    name: str
    prefix: str
    service: str  # host:port (the primary backend)
    rewrite: str = "/"
    # Traffic splitting (the seldon abtest/mab/canary surface,
    # /root/reference/kubeflow/seldon/prototypes, core.libsonnet:305):
    # weighted variants — each request is routed to one backend drawn by
    # weight. Empty = all traffic to `service`.
    backends: tuple = ()  # ((host:port, weight), ...)
    # "weighted": static draw by weight. "epsilon-greedy": the seldon
    # multi-armed-bandit router (epsilon-greedy prototype) — explore a
    # random variant with probability epsilon, otherwise exploit the
    # best observed reward; rewards come from response status (5xx/
    # connect-fail = 0) or the admin feedback endpoint.
    # "prefix-affine": the replica-pool router — rendezvous-hash the
    # prompt's leading tokens over the healthy backends so requests
    # sharing a prefix land on ONE replica (its prefix cache keeps
    # hitting), spilling to the least-loaded backend when the affine
    # replica is over ``pressure`` in-flight requests.
    strategy: str = "weighted"
    epsilon: float = 0.1
    # prefix-affine knobs: leading tokens hashed into the routing key,
    # and the per-backend in-flight bound past which the affine pick
    # spills (0 = never spill).
    affinity_tokens: int = 32
    pressure: int = 0
    # KV-fill fraction past which the affine pick spills (0 = ignore).
    # The signal comes from the gateway's staleness-bounded scrape of
    # each backend's serving_kv_bytes_in_use/_total; an unscrapeable
    # backend contributes NO signal (never treated as empty).
    kv_pressure: float = 0.0
    # Disaggregated prefill pool: when non-empty, generate requests on
    # this route ride the two-hop relay — the gateway affine-picks a
    # prefill backend here, asks it to :prefill and push the prompt KV
    # to the chosen decode backend (one of ``backends``), then relays
    # the :predict to the decode backend as usual.
    prefill_backends: tuple = ()  # ((host:port, weight), ...)
    # Shadow/mirror target: every request is also sent fire-and-forget to
    # this backend; its response is discarded and its failures invisible.
    shadow: str = ""
    # Outlier detection (seldon outlier-detector-v1alpha2 surface): score
    # each prediction request's feature against a running window;
    # |z| > threshold tags the response and counts into the outlier rate.
    # 0 disables.
    outlier_threshold: float = 0.0
    outlier_window: int = 100
    # Identity-token policy for this route: "" = the gateway default
    # (verify when a JwtVerifier is configured), "off" = this route is
    # exempt (the per-route face of iap.libsonnet:600's bypass_jwt),
    # "required" = bearer token only, no session fallback.
    jwt: str = ""
    # Per-tenant overload shedding: ((tenant, rate, burst), ...) token
    # buckets — a request whose tenant (X-Tenant header, else the
    # authenticated identity, else "default") is over rate answers 429
    # with a computed Retry-After at the GATEWAY, before any upstream
    # work. qos_default_rate/burst cover tenants without their own
    # entry (0 = unlimited). Bucket state lives on the Gateway, keyed
    # (route, tenant).
    qos_tenants: tuple = ()  # ((tenant, rate, burst), ...)
    qos_default_rate: float = 0.0
    qos_default_burst: float = 0.0

    def qos_for(self, tenant: str) -> tuple[float, float]:
        """(rate, burst) governing ``tenant`` on this route."""
        for name, rate, burst in self.qos_tenants:
            if name == tenant:
                return rate, burst
        return self.qos_default_rate, self.qos_default_burst

    @property
    def qos_active(self) -> bool:
        return bool(self.qos_tenants) or self.qos_default_rate > 0

    def pick_service(self, rng) -> str:
        if not self.backends:
            return self.service
        services = [b[0] for b in self.backends]
        weights = [b[1] for b in self.backends]
        return rng.choices(services, weights=weights)[0]

    def target_for(self, path: str, service: str | None = None) -> str:
        """Rewrite `path` (which startswith prefix) onto the backend."""
        rest = path[len(self.prefix):]
        base = (self.rewrite if self.rewrite.endswith("/")
                else self.rewrite + "/")
        return ("http://" + (service or self.service) + base
                + rest.lstrip("/"))


def routes_from_service(svc: dict) -> list[Route]:
    raw = svc.get("metadata", {}).get("annotations", {}).get(
        GATEWAY_ROUTE_ANNOTATION
    )
    if not raw:
        return []
    try:
        specs = yaml.safe_load(raw)
    except yaml.YAMLError:
        log.warning("bad route annotation on %s",
                    svc["metadata"].get("name"))
        return []
    if isinstance(specs, dict):
        specs = [specs]
    routes = []
    for spec in specs or []:
        try:
            backends = tuple(
                (b["service"], float(b.get("weight", 1)))
                for b in spec.get("backends", [])
            )
            if backends and any(w < 0 for _s, w in backends):
                raise ValueError("negative backend weight")
            if backends and not any(w > 0 for _s, w in backends):
                raise ValueError("all backend weights zero")
            service = spec.get("service") or (
                backends[0][0] if backends else None
            )
            if not service:
                raise KeyError("service")
            strategy = spec.get("strategy", "weighted")
            if strategy not in ("weighted", "epsilon-greedy",
                                "prefix-affine"):
                raise ValueError(f"unknown strategy {strategy!r}")
            epsilon = float(spec.get("epsilon", 0.1))
            if not 0.0 <= epsilon <= 1.0:
                raise ValueError("epsilon must be in [0, 1]")
            affinity_tokens = int(spec.get("affinity_tokens", 32))
            if affinity_tokens < 1:
                raise ValueError("affinity_tokens must be >= 1")
            pressure = int(spec.get("pressure", 0))
            if pressure < 0:
                raise ValueError("pressure must be >= 0")
            kv_pressure = float(spec.get("kv_pressure", 0.0))
            if not 0.0 <= kv_pressure <= 1.0:
                raise ValueError("kv_pressure must be in [0, 1]")
            prefill_backends = tuple(
                (b["service"], float(b.get("weight", 1)))
                for b in spec.get("prefill_backends", [])
            )
            if prefill_backends and strategy != "prefix-affine":
                # The two-hop relay hashes the prompt; without the
                # affine strategy nothing reads the prefill pool.
                raise ValueError("prefill_backends requires the "
                                 "prefix-affine strategy")
            if strategy == "prefix-affine" and not spec.get("backends"):
                # One backend is nothing to hash over — surface the
                # misconfiguration instead of silently direct-routing.
                raise ValueError("prefix-affine needs a backends pool")
            outlier = spec.get("outlier", {}) or {}
            outlier_threshold = float(outlier.get("threshold", 0.0))
            outlier_window = int(outlier.get("window", 100))
            if outlier_threshold < 0:
                raise ValueError("outlier threshold must be >= 0")
            if outlier_window < 2:
                raise ValueError("outlier window must be >= 2")
            jwt = str(spec.get("jwt", ""))
            if jwt not in ("", "off", "required"):
                raise ValueError(f"jwt must be 'off' or 'required', "
                                 f"got {jwt!r}")
            qos = spec.get("qos", {}) or {}
            qos_tenants = tuple(
                (str(name),
                 float((t or {}).get("rate", 0)),
                 float((t or {}).get("burst", 0)))
                for name, t in sorted(
                    (qos.get("tenants", {}) or {}).items())
            )
            if any(r < 0 or b < 0 for _n, r, b in qos_tenants):
                raise ValueError("qos rate/burst must be >= 0")
            qos_default = qos.get("default", {}) or {}
            qos_default_rate = float(qos_default.get("rate", 0))
            qos_default_burst = float(qos_default.get("burst", 0))
            if qos_default_rate < 0 or qos_default_burst < 0:
                raise ValueError("qos default rate/burst must be >= 0")
            routes.append(Route(
                jwt=jwt,
                name=spec["name"], prefix=spec["prefix"],
                service=service, rewrite=spec.get("rewrite", "/"),
                backends=backends, strategy=strategy, epsilon=epsilon,
                affinity_tokens=affinity_tokens, pressure=pressure,
                kv_pressure=kv_pressure,
                prefill_backends=prefill_backends,
                shadow=spec.get("shadow", ""),
                outlier_threshold=outlier_threshold,
                outlier_window=outlier_window,
                qos_tenants=qos_tenants,
                qos_default_rate=qos_default_rate,
                qos_default_burst=qos_default_burst,
            ))
        except (KeyError, TypeError, ValueError) as e:
            log.warning("bad route spec in %s: %s",
                        svc["metadata"].get("name"), e)
    return routes


class RouteTable:
    """Longest-prefix route lookup, refreshed from Service annotations."""

    def __init__(self) -> None:
        self._routes: list[Route] = []
        self._lock = threading.Lock()

    def set_routes(self, routes: list[Route]) -> None:
        with self._lock:
            # Longest prefix first; on equal prefixes a split/shadow route
            # beats a plain one (a serving-route canary for a model must
            # override the model Service's own direct route, not lose the
            # tie to listing order), then name for determinism.
            self._routes = sorted(
                routes,
                key=lambda r: (-len(r.prefix),
                               0 if (r.backends or r.shadow) else 1,
                               r.name),
            )

    def refresh(self, client: K8sClient, namespace: str | None = None) -> int:
        routes = []
        for svc in client.list("v1", "Service", namespace):
            routes.extend(routes_from_service(svc))
        self.set_routes(routes)
        return len(routes)

    def match(self, path: str) -> Route | None:
        with self._lock:
            for r in self._routes:
                if path.startswith(r.prefix):
                    return r
        return None

    def snapshot(self) -> list[dict]:
        with self._lock:
            # Copies, not the live __dict__ of the frozen Routes — callers
            # (the admin handler) annotate these per request.
            return [dict(vars(r)) for r in self._routes]

    def find(self, name: str) -> Route | None:
        with self._lock:
            return next((r for r in self._routes if r.name == name), None)
