"""Route model + annotation-discovered route table.

The ambassador mapping layer (kubeflow/common/ambassador.libsonnet:7-226):
every platform Service that wants routing carries a
`kubeflow-tpu.org/gateway-route` annotation (the `getambassador.io/config`
pattern — route spec {name, prefix, service, rewrite}); the gateway
watches Services and keeps a longest-prefix route table.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from dataclasses import dataclass

import yaml

from kubeflow_tpu.k8s.client import K8sClient
from kubeflow_tpu.manifests.core import GATEWAY_ROUTE_ANNOTATION

log = logging.getLogger(__name__)


def stable_hash01(key: bytes, salt: bytes = b"") -> float:
    """Deterministic uniform [0, 1) from a routing key — the same key
    maps to the same point on every gateway process forever (unlike
    Python's seeded ``hash``), so a canary split holds its assignment
    across gateway restarts and replicas."""
    h = hashlib.blake2b(salt + key, digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0**64


@dataclass(frozen=True)
class Route:
    name: str
    prefix: str
    service: str  # host:port (the primary backend)
    rewrite: str = "/"
    # Traffic splitting (the seldon abtest/mab/canary surface,
    # /root/reference/kubeflow/seldon/prototypes, core.libsonnet:305):
    # weighted variants — each request is routed to one backend drawn by
    # weight. Empty = all traffic to `service`.
    backends: tuple = ()  # ((host:port, weight), ...)
    # "weighted": static draw by weight. "epsilon-greedy": the seldon
    # multi-armed-bandit router (epsilon-greedy prototype) — explore a
    # random variant with probability epsilon, otherwise exploit the
    # best observed reward; rewards come from response status (5xx/
    # connect-fail = 0) or the admin feedback endpoint.
    # "prefix-affine": the replica-pool router — rendezvous-hash the
    # prompt's leading tokens over the healthy backends so requests
    # sharing a prefix land on ONE replica (its prefix cache keeps
    # hitting), spilling to the least-loaded backend when the affine
    # replica is over ``pressure`` in-flight requests.
    strategy: str = "weighted"
    epsilon: float = 0.1
    # prefix-affine knobs: leading tokens hashed into the routing key,
    # and the per-backend in-flight bound past which the affine pick
    # spills (0 = never spill).
    affinity_tokens: int = 32
    pressure: int = 0
    # KV-fill fraction past which the affine pick spills (0 = ignore).
    # The signal comes from the gateway's staleness-bounded scrape of
    # each backend's serving_kv_bytes_in_use/_total; an unscrapeable
    # backend contributes NO signal (never treated as empty).
    kv_pressure: float = 0.0
    # Disaggregated prefill pool: when non-empty, generate requests on
    # this route ride the two-hop relay — the gateway affine-picks a
    # prefill backend here, asks it to :prefill and push the prompt KV
    # to the chosen decode backend (one of ``backends``), then relays
    # the :predict to the decode backend as usual.
    prefill_backends: tuple = ()  # ((host:port, weight), ...)
    # "hash-split": the progressive-delivery strategy — version groups
    # (``splits``) each own a traffic weight, and a request is assigned
    # to a group by STABLE hash of its affinity key, so every request
    # sharing a prefix sees ONE model version (the per-request
    # rng.choices draw would interleave versions within a conversation
    # and poison both versions' prefix caches). Within the chosen
    # group the pick is rendezvous-affine, same as prefix-affine.
    splits: tuple = ()  # ((version, weight, (host:port, ...)), ...)
    # Shadow/mirror target: every request is also sent fire-and-forget to
    # this backend; its response is discarded and its failures invisible.
    shadow: str = ""
    # Fraction of requests mirrored to ``shadow``, decided by stable
    # hash of the affinity key (salted differently from the split hash
    # so shadow sampling doesn't correlate with version assignment).
    # 1.0 = mirror everything (the legacy behavior).
    shadow_fraction: float = 1.0
    # Outlier detection (seldon outlier-detector-v1alpha2 surface): score
    # each prediction request's feature against a running window;
    # |z| > threshold tags the response and counts into the outlier rate.
    # 0 disables.
    outlier_threshold: float = 0.0
    outlier_window: int = 100
    # Identity-token policy for this route: "" = the gateway default
    # (verify when a JwtVerifier is configured), "off" = this route is
    # exempt (the per-route face of iap.libsonnet:600's bypass_jwt),
    # "required" = bearer token only, no session fallback.
    jwt: str = ""
    # Per-tenant overload shedding: ((tenant, rate, burst), ...) token
    # buckets — a request whose tenant (X-Tenant header, else the
    # authenticated identity, else "default") is over rate answers 429
    # with a computed Retry-After at the GATEWAY, before any upstream
    # work. qos_default_rate/burst cover tenants without their own
    # entry (0 = unlimited). Bucket state lives on the Gateway, keyed
    # (route, tenant).
    qos_tenants: tuple = ()  # ((tenant, rate, burst), ...)
    qos_default_rate: float = 0.0
    qos_default_burst: float = 0.0

    def qos_for(self, tenant: str) -> tuple[float, float]:
        """(rate, burst) governing ``tenant`` on this route."""
        for name, rate, burst in self.qos_tenants:
            if name == tenant:
                return rate, burst
        return self.qos_default_rate, self.qos_default_burst

    @property
    def qos_active(self) -> bool:
        return bool(self.qos_tenants) or self.qos_default_rate > 0

    def pick_service(self, rng) -> str:
        if not self.backends:
            return self.service
        services = [b[0] for b in self.backends]
        weights = [b[1] for b in self.backends]
        return rng.choices(services, weights=weights)[0]

    def pick_split(self, key: bytes) -> tuple | None:
        """Assign a routing key to one version group by stable hash:
        the key's hash point falls into exactly one group's slice of
        the cumulative weight space. Returns ``(version, weight,
        backends)`` or None when the route has no splits."""
        if not self.splits:
            return None
        total = sum(s[1] for s in self.splits)
        if total <= 0:
            return self.splits[0]
        point = stable_hash01(key, b"split:") * total
        acc = 0.0
        for split in self.splits:
            acc += split[1]
            if point < acc:
                return split
        return self.splits[-1]

    def mirror_sample(self, key: bytes) -> bool:
        """Whether this request's key falls inside the shadow fraction
        (deterministic per key: an affine prefix is either always or
        never mirrored, so the candidate's prefix cache sees coherent
        conversations instead of random single turns)."""
        if self.shadow_fraction >= 1.0:
            return True
        if self.shadow_fraction <= 0.0:
            return False
        return stable_hash01(key, b"shadow:") < self.shadow_fraction

    def version_of(self, service: str) -> str:
        """The split version name owning ``service`` ("" if unsplit)."""
        for version, _w, members in self.splits:
            if service in members:
                return version
        return ""

    def target_for(self, path: str, service: str | None = None) -> str:
        """Rewrite `path` (which startswith prefix) onto the backend."""
        rest = path[len(self.prefix):]
        base = (self.rewrite if self.rewrite.endswith("/")
                else self.rewrite + "/")
        return ("http://" + (service or self.service) + base
                + rest.lstrip("/"))


def routes_from_service(svc: dict) -> list[Route]:
    raw = svc.get("metadata", {}).get("annotations", {}).get(
        GATEWAY_ROUTE_ANNOTATION
    )
    if not raw:
        return []
    try:
        specs = yaml.safe_load(raw)
    except yaml.YAMLError:
        log.warning("bad route annotation on %s",
                    svc["metadata"].get("name"))
        return []
    if isinstance(specs, dict):
        specs = [specs]
    routes = []
    for spec in specs or []:
        try:
            backends = tuple(
                (b["service"], float(b.get("weight", 1)))
                for b in spec.get("backends", [])
            )
            if backends and any(w < 0 for _s, w in backends):
                raise ValueError("negative backend weight")
            if backends and not any(w > 0 for _s, w in backends):
                raise ValueError("all backend weights zero")
            service = spec.get("service") or (
                backends[0][0] if backends else None
            )
            if not service:
                raise KeyError("service")
            strategy = spec.get("strategy", "weighted")
            if strategy not in ("weighted", "epsilon-greedy",
                                "prefix-affine", "hash-split"):
                raise ValueError(f"unknown strategy {strategy!r}")
            splits = []
            seen_versions: set[str] = set()
            for s in spec.get("splits", []) or []:
                version = str(s.get("version", "")).strip()
                if not version:
                    raise ValueError("split missing version name")
                if version in seen_versions:
                    raise ValueError(
                        f"duplicate split version {version!r}")
                seen_versions.add(version)
                weight = float(s.get("weight", 0))
                if weight < 0:
                    raise ValueError("negative split weight")
                members = tuple(str(m) for m in s.get("backends", []))
                if not members:
                    raise ValueError(
                        f"split {version!r} has no backends")
                splits.append((version, weight, members))
            splits = tuple(splits)
            if splits and strategy != "hash-split":
                raise ValueError("splits requires the hash-split "
                                 "strategy")
            if strategy == "hash-split":
                if not splits:
                    raise ValueError("hash-split needs a splits list")
                if not any(w > 0 for _v, w, _m in splits):
                    raise ValueError("all split weights zero")
                if not spec.get("backends"):
                    # backends stays the flattened union across splits
                    # — health probing and the admin surface read it.
                    raise ValueError("hash-split needs a backends pool")
            shadow_fraction = float(spec.get("shadow_fraction", 1.0))
            if not 0.0 <= shadow_fraction <= 1.0:
                raise ValueError("shadow_fraction must be in [0, 1]")
            epsilon = float(spec.get("epsilon", 0.1))
            if not 0.0 <= epsilon <= 1.0:
                raise ValueError("epsilon must be in [0, 1]")
            affinity_tokens = int(spec.get("affinity_tokens", 32))
            if affinity_tokens < 1:
                raise ValueError("affinity_tokens must be >= 1")
            pressure = int(spec.get("pressure", 0))
            if pressure < 0:
                raise ValueError("pressure must be >= 0")
            kv_pressure = float(spec.get("kv_pressure", 0.0))
            if not 0.0 <= kv_pressure <= 1.0:
                raise ValueError("kv_pressure must be in [0, 1]")
            prefill_backends = tuple(
                (b["service"], float(b.get("weight", 1)))
                for b in spec.get("prefill_backends", [])
            )
            if prefill_backends and strategy != "prefix-affine":
                # The two-hop relay hashes the prompt; without the
                # affine strategy nothing reads the prefill pool.
                raise ValueError("prefill_backends requires the "
                                 "prefix-affine strategy")
            if strategy == "prefix-affine" and not spec.get("backends"):
                # One backend is nothing to hash over — surface the
                # misconfiguration instead of silently direct-routing.
                raise ValueError("prefix-affine needs a backends pool")
            outlier = spec.get("outlier", {}) or {}
            outlier_threshold = float(outlier.get("threshold", 0.0))
            outlier_window = int(outlier.get("window", 100))
            if outlier_threshold < 0:
                raise ValueError("outlier threshold must be >= 0")
            if outlier_window < 2:
                raise ValueError("outlier window must be >= 2")
            jwt = str(spec.get("jwt", ""))
            if jwt not in ("", "off", "required"):
                raise ValueError(f"jwt must be 'off' or 'required', "
                                 f"got {jwt!r}")
            qos = spec.get("qos", {}) or {}
            qos_tenants = tuple(
                (str(name),
                 float((t or {}).get("rate", 0)),
                 float((t or {}).get("burst", 0)))
                for name, t in sorted(
                    (qos.get("tenants", {}) or {}).items())
            )
            if any(r < 0 or b < 0 for _n, r, b in qos_tenants):
                raise ValueError("qos rate/burst must be >= 0")
            qos_default = qos.get("default", {}) or {}
            qos_default_rate = float(qos_default.get("rate", 0))
            qos_default_burst = float(qos_default.get("burst", 0))
            if qos_default_rate < 0 or qos_default_burst < 0:
                raise ValueError("qos default rate/burst must be >= 0")
            routes.append(Route(
                jwt=jwt,
                name=spec["name"], prefix=spec["prefix"],
                service=service, rewrite=spec.get("rewrite", "/"),
                backends=backends, strategy=strategy, epsilon=epsilon,
                affinity_tokens=affinity_tokens, pressure=pressure,
                kv_pressure=kv_pressure,
                prefill_backends=prefill_backends,
                splits=splits,
                shadow=spec.get("shadow", ""),
                shadow_fraction=shadow_fraction,
                outlier_threshold=outlier_threshold,
                outlier_window=outlier_window,
                qos_tenants=qos_tenants,
                qos_default_rate=qos_default_rate,
                qos_default_burst=qos_default_burst,
            ))
        except (KeyError, TypeError, ValueError) as e:
            log.warning("bad route spec in %s: %s",
                        svc["metadata"].get("name"), e)
    return routes


class RouteTable:
    """Longest-prefix route lookup, refreshed from Service annotations."""

    def __init__(self) -> None:
        self._routes: list[Route] = []
        self._lock = threading.Lock()

    def set_routes(self, routes: list[Route]) -> None:
        with self._lock:
            # Longest prefix first; on equal prefixes a split/shadow route
            # beats a plain one (a serving-route canary for a model must
            # override the model Service's own direct route, not lose the
            # tie to listing order), then name for determinism.
            self._routes = sorted(
                routes,
                key=lambda r: (-len(r.prefix),
                               0 if (r.backends or r.shadow) else 1,
                               r.name),
            )

    def refresh(self, client: K8sClient, namespace: str | None = None) -> int:
        routes = []
        for svc in client.list("v1", "Service", namespace):
            routes.extend(routes_from_service(svc))
        self.set_routes(routes)
        return len(routes)

    def match(self, path: str) -> Route | None:
        with self._lock:
            for r in self._routes:
                if path.startswith(r.prefix):
                    return r
        return None

    def snapshot(self) -> list[dict]:
        with self._lock:
            # Copies, not the live __dict__ of the frozen Routes — callers
            # (the admin handler) annotate these per request.
            return [dict(vars(r)) for r in self._routes]

    def find(self, name: str) -> Route | None:
        with self._lock:
            return next((r for r in self._routes if r.name == name), None)
