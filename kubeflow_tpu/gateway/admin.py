"""The admin/ops surface: /routes, /upstreams, /metrics, and the seldon
send-feedback analogue (POST /routes/<name>/feedback) steering
epsilon-greedy routes.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler

from kubeflow_tpu.observability.metrics import render_prometheus
from kubeflow_tpu.observability.tracing import render_debug


def make_admin_handler(gw):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/routes":
                routes = gw.table.snapshot()
                for r in routes:
                    if r.get("strategy") == "epsilon-greedy":
                        r["bandit"] = gw.bandit.snapshot(r["name"])
                    if r.get("outlier_threshold"):
                        r["outliers"] = gw.outliers.snapshot(r["name"])
                body = json.dumps(routes).encode()
                ctype = "application/json"
            elif self.path == "/upstreams":
                # Upstream health + circuit state, per backend (the
                # envoy clusters/outlier admin surface), plus the
                # in-flight depth the prefix-affine spill reads.
                snap = gw.health.snapshot()
                for svc, depth in gw.load.snapshot().items():
                    snap.setdefault(svc, {})["in_flight"] = depth
                # Last-scraped KV fill (None = no signal) alongside the
                # depth, so operators see both spill inputs in one view.
                for svc, fill in gw.kv_fill.snapshot().items():
                    snap.setdefault(svc, {})["kv_fill"] = fill
                body = json.dumps(snap).encode()
                ctype = "application/json"
            elif self.path == "/metrics":
                # Counters through the shared dict renderer (typed by
                # the _total suffix), histograms (per-route upstream
                # latency) through the gateway's registry — one
                # exposition renderer for the whole platform.
                body = (render_prometheus({
                    "gateway_requests_total": gw.requests_total,
                    "gateway_errors_total": gw.errors_total,
                    "gateway_upgrade_tunnels_total": gw.tunnels_total,
                    "gateway_shadow_requests_total": gw.shadow_total,
                    "gateway_retries_total": gw.retries_total,
                    "gateway_affine_spills_total": gw.affine_spills,
                    "gateway_directory_hits_total": gw.directory_hits,
                    "gateway_qos_shed_total": gw.qos_shed_total,
                    "gateway_body_rejected_total":
                        gw.body_rejected_total,
                    "gateway_handoffs_total": gw.handoffs_total,
                    "gateway_handoff_failures_total":
                        gw.handoff_failures,
                    "gateway_kv_scrapes_total": gw.kv_fill.scrapes,
                    "gateway_kv_scrape_failures_total":
                        gw.kv_fill.scrape_failures,
                    "gateway_outliers_total": gw.outliers.totals()[0],
                    "gateway_outlier_scored_total":
                        gw.outliers.totals()[1],
                    "gateway_jwt_verified_total":
                        getattr(gw.jwt_verifier, "verified_total", 0),
                    "gateway_jwt_rejected_total":
                        getattr(gw.jwt_verifier, "rejected_total", 0),
                }) + gw.registry.render()).encode()
                ctype = "text/plain"
            elif self.path == "/metricsz":
                # Fleet rollup (JSON, not prometheus exposition): the
                # per-route affinity outcome counters — affine hits vs
                # pressure spills vs directory-steered spills — plus
                # the prefix-directory stats and the per-backend
                # depth/KV-fill the spill decisions read. One curl
                # answers "is locality holding, and when it breaks,
                # does the directory catch the spill?" — previously
                # spills were only visible per-replica.
                with gw._affinity_lock:
                    routes = {name: dict(per)
                              for name, per in gw.route_affinity.items()}
                totals = {"affine": 0, "spill": 0, "directory": 0}
                for per in routes.values():
                    for k in totals:
                        totals[k] += per.get(k, 0)
                upstreams = {}
                for svc, depth in gw.load.snapshot().items():
                    upstreams.setdefault(svc, {})["in_flight"] = depth
                for svc, fill in gw.kv_fill.snapshot().items():
                    upstreams.setdefault(svc, {})["kv_fill"] = fill
                body = json.dumps({
                    "routes": routes,
                    "totals": totals,
                    "affine_spills_total": gw.affine_spills,
                    "directory_hits_total": gw.directory_hits,
                    "directory": gw.kv_directory.stats(),
                    "upstreams": upstreams,
                }).encode()
                ctype = "application/json"
            elif self.path.partition("?")[0] == "/debug/requests":
                body, ctype = render_debug(gw.trace,
                                           self.path.partition("?")[2])
            elif self.path in ("/healthz", "/readyz"):
                body, ctype = b'{"status":"ok"}', "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            """POST /routes/<name>/feedback {"service", "reward"} —
            the seldon /send-feedback analogue: callers grade a
            variant's answer (0..1) after the fact, steering the
            epsilon-greedy router beyond what status codes reveal."""
            parts = self.path.strip("/").split("/")
            if (len(parts) != 3 or parts[0] != "routes"
                    or parts[2] != "feedback"):
                self.send_response(404)
                self.end_headers()
                return
            route = gw.table.find(parts[1])
            if route is None:
                body = json.dumps(
                    {"error": f"no route {parts[1]!r}"}).encode()
                self.send_response(404)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length))
                service = payload["service"]
                reward = float(payload["reward"])
                if not 0.0 <= reward <= 1.0:
                    raise ValueError("reward must be in [0, 1]")
                # Only the route's real variants are gradeable — a
                # typo'd service must not 200-and-steer-nothing, and
                # validation bounds the stats table to routes×backends.
                variants = {b[0] for b in route.backends}
                if service not in variants:
                    raise ValueError(
                        f"service {service!r} is not a variant of "
                        f"route {parts[1]!r}")
            except (ValueError, KeyError, TypeError) as e:
                body = json.dumps({"error": str(e)}).encode()
                self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            gw.bandit.record(parts[1], service, reward)
            body = json.dumps(
                {"ok": True,
                 "stats": gw.bandit.snapshot(parts[1])}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return Handler

