"""API gateway: annotation-discovered reverse proxy.

The ambassador analogue (kubeflow/common/ambassador.libsonnet:7-226): every
platform Service that wants routing carries a
`kubeflow-tpu.org/gateway-route` annotation (the `getambassador.io/config`
pattern — route spec {name, prefix, service, rewrite}); the gateway watches
Services, keeps a longest-prefix route table, and proxies requests to the
backing service. Optional forward-auth: every request is checked against the
gatekeeper's /auth endpoint first (the IAP/basic-auth ingress role,
kubeflow/common/basic-auth.libsonnet).

Proxying is streaming end to end: response bodies are relayed chunk by
chunk as the upstream produces them (chunked re-encoding when the upstream
length is unknown — SSE/token streams flow unbuffered), and an HTTP/1.1
Upgrade handshake (notebooks' websocket kernels,
kubeflow/jupyter/jupyter.libsonnet:97-106 `use_websocket: true`) switches
the connection to a transparent bidirectional TCP tunnel.
"""

from __future__ import annotations

import logging
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from kubeflow_tpu.gateway.admin import make_admin_handler
from kubeflow_tpu.gateway.proxy import make_proxy_handler
from kubeflow_tpu.observability.metrics import MetricRegistry
from kubeflow_tpu.observability.tracing import TraceStore
from kubeflow_tpu.serving.kv_directory import KvDirectory
from kubeflow_tpu.gateway.resilience import (
    BackendLoad,
    BanditStats,
    KvFillCache,
    OutlierStats,
    UpstreamHealth,
)
from kubeflow_tpu.gateway.routing import Route, RouteTable, routes_from_service

__all__ = [
    "BackendLoad", "BanditStats", "Gateway", "KvFillCache",
    "OutlierStats", "Route", "RouteTable", "UpstreamHealth",
    "routes_from_service",
]

log = logging.getLogger(__name__)


class Gateway:
    """The proxy + admin servers.

    ``resolve`` maps a route's `host:port` service address to the address to
    actually dial — identity in-cluster, overridden in tests to point at
    local fixture backends.
    """

    def __init__(
        self,
        table: RouteTable,
        *,
        port: int = 8080,
        admin_port: int = 8877,
        auth_url: str = "",
        resolve: Callable[[str], str] | None = None,
        certfile: str = "",
        keyfile: str = "",
        cert_reload_seconds: float = 5.0,
        redirect_port: int | None = None,
        redirect_target_port: int | None = None,
        challenge_lookup: Callable[[str], str | None] | None = None,
        upstream_timeout: float = 60.0,
        max_body_bytes: int = 0,
        health: UpstreamHealth | None = None,
        probe_interval: float = 2.0,
        retry_budget: float = 0.2,
        jwt_verifier=None,
        rng=None,
    ):
        self.table = table
        self.port = port
        self.admin_port = admin_port
        self.auth_url = auth_url
        self.resolve = resolve or (lambda addr: addr)
        self.upstream_timeout = upstream_timeout
        # Declared-request-size ceiling (0 = unbounded): a long-context
        # prompt larger than this answers 413 before any body byte is
        # read, so one oversized client can't balloon gateway memory.
        self.max_body_bytes = max_body_bytes
        self.body_rejected_total = 0
        # TLS termination at the gateway (the iap-ingress/cert-manager
        # role, kubeflow/gcp/iap.libsonnet): cert+key mounted from a
        # Secret; empty = plain HTTP (in-mesh or behind an LB). The
        # mounted files are WATCHED: when the certificate controller
        # rotates the secret, new handshakes pick up the new cert from
        # the same SSLContext without dropping the listener or any
        # established connection (cert_reload_seconds poll; 0 disables).
        self.certfile = certfile
        self.keyfile = keyfile
        self.cert_reload_seconds = cert_reload_seconds
        # components/https-redirect analogue: a plain-HTTP listener that
        # 301s every request to the HTTPS entrypoint. None = disabled.
        # ``redirect_target_port`` is the EXTERNALLY advertised HTTPS port
        # (None = omit, the :443 default) — behind a Service mapping
        # 443→bind-port, the bind port must never leak into Location.
        self.redirect_port = redirect_port
        self.redirect_target_port = redirect_target_port
        # ACME HTTP-01: serves /.well-known/acme-challenge/<token> from
        # the certificate controller's published challenges (the path a
        # letsencrypt-style validator fetches pre-issuance).
        self.challenge_lookup = challenge_lookup
        self.cert_reloads = 0
        # Weight-draw source for traffic splitting (seedable in tests).
        self.rng = rng or random.Random()
        # Reward averages for epsilon-greedy (bandit) routes.
        self.bandit = BanditStats()
        # Per-route anomaly scoring (seldon outlier-detector surface).
        self.outliers = OutlierStats()
        # Upstream health/circuit breaking: passive per-request
        # observations + an active prober thread (probe_interval; 0
        # disables the prober, passive observation still applies).
        self.health = health or UpstreamHealth()
        self.probe_interval = probe_interval
        # Idempotent-retry budget (envoy-style): GET/HEAD requests that
        # hit a dead backend may retry ONCE against a different healthy
        # backend, as long as retries stay under this fraction of
        # requests — a hard cap so retries can't amplify an outage.
        self.retry_budget = retry_budget
        # Identity-token verification (gateway/jwt_auth.JwtVerifier) —
        # the envoy jwt-auth filter role (iap.libsonnet:589-600). None =
        # no bearer-token requirement. When BOTH a verifier and a
        # forward-auth URL are configured, a request passes with EITHER a
        # valid token OR a valid session (IAP's browser-login + SA
        # id-token duality).
        self.jwt_verifier = jwt_verifier
        self.retries_total = 0
        self.requests_total = 0
        self.errors_total = 0
        self.tunnels_total = 0
        self.shadow_total = 0
        # Per-backend in-flight depth — the pressure signal the
        # prefix-affine replica-pool strategy spills on (exact for the
        # traffic this gateway carries; no scrape freshness to trust).
        self.load = BackendLoad()
        self.affine_spills = 0
        # Gateway-side KV-fill scrape (staleness-bounded): the replica
        # pool signal the in-flight depth can't see — a backend whose
        # block pool is nearly full defers admissions long before its
        # gateway-visible depth grows. Folded into the prefix-affine
        # spill decision when the route sets kv_pressure.
        self.kv_fill = KvFillCache()
        # Fleet KV economy: the gateway-side prefix→holder directory.
        # Every prefix-affine placement publishes its chosen backend as
        # a holder for the request's affinity key, and a SPILL consults
        # the directory first — a spilled request lands on a backend
        # already advertising its prefix (warm trie, or peer-importable
        # KV) instead of merely the least-loaded one. Hints, not truth:
        # the replicas validate on pull, so a stale gateway hint costs
        # one ordinary prefill.
        self.kv_directory = KvDirectory(2048)
        self.directory_hits = 0   # spills steered to an advertised holder
        # Per-route affinity outcome counters for the /metricsz rollup:
        # route name → {"affine": n, "spill": n, "directory": n}.
        self.route_affinity: dict = {}
        self._affinity_lock = threading.Lock()
        # Disaggregated two-hop relay counters (prefill_backends routes).
        self.handoffs_total = 0
        self.handoff_failures = 0
        # Per-tenant overload shedding (routes carrying a qos spec):
        # token buckets keyed (route, tenant), and how many requests
        # were answered 429 + Retry-After instead of queued into a
        # collapsing upstream.
        self.qos_shed_total = 0
        self._qos_buckets: dict = {}
        self._qos_lock = threading.Lock()
        # Shared observability registry (served on the admin /metrics):
        # per-route upstream latency distributions — the signal a
        # metric-driven autoscaler reads per backend pool.
        self.registry = MetricRegistry()
        self.upstream_latency = self.registry.histogram(
            "gateway_upstream_latency_seconds",
            "Upstream request latency (connect to response headers)",
            labels=("route",))
        # Progressive-delivery families: request counts, shadow-mirror
        # counts, and upstream-latency distributions labeled by model
        # version — the per-version evidence a rollout gate compares
        # (candidate p99 vs incumbent p99 on the SAME exposition).
        self.version_requests = self.registry.counter(
            "gateway_version_requests_total",
            "Requests routed per model version on a split route",
            labels=("route", "version"))
        self.version_shadow_total = self.registry.counter(
            "gateway_version_shadow_mirrors_total",
            "Shadow requests mirrored per model version",
            labels=("route", "version"))
        self.version_upstream_latency = self.registry.histogram(
            "gateway_version_upstream_latency_seconds",
            "Upstream request latency per model version "
            "(shadow mirrors included)",
            labels=("route", "version"))
        # Per-request timelines (received → upstream → relayed), ring-
        # bounded, served at the admin /debug/requests. The request id
        # recorded here is the same X-Request-ID forwarded upstream, so
        # a gateway hop and its decoder stream correlate by one id.
        self.trace = TraceStore()
        self._proxy: ThreadingHTTPServer | None = None
        self._admin: ThreadingHTTPServer | None = None
        self._redirect: ThreadingHTTPServer | None = None
        self._ssl_ctx = None
        self._cert_watch_stop = threading.Event()

    def note_affinity(self, route_name: str, kind: str) -> None:
        """Count one prefix-affine placement outcome on a route:
        ``affine`` (landed on the rendezvous pick), ``spill`` (pressure
        pushed it off), or ``directory`` (a spill steered to a backend
        the prefix directory advertised). The /metricsz rollup reads
        these per route — spills were previously only visible
        per-replica."""
        with self._affinity_lock:
            per = self.route_affinity.setdefault(
                route_name, {"affine": 0, "spill": 0, "directory": 0})
            per[kind] = per.get(kind, 0) + 1
            if kind == "directory":
                self.directory_hits += 1

    def _retry_allowed(self) -> bool:
        return (self.retries_total + 1) <= self.retry_budget * max(
            self.requests_total, 1
        )

    def qos_admit(self, route, tenant: str) -> tuple[bool, float]:
        """Token-bucket admission for one request on a qos-carrying
        route: (admitted, retry_after_s). Buckets refill continuously
        at the route's per-tenant rate; an unknown tenant gets its own
        bucket at the route default (so one abusive id cannot drain a
        shared bucket for everyone else)."""
        from kubeflow_tpu.serving.qos import TokenBucket

        rate, burst = route.qos_for(tenant)
        if rate <= 0:
            return True, 0.0
        now = time.monotonic()
        with self._qos_lock:
            bucket = self._qos_buckets.get((route.name, tenant))
            if bucket is None:
                bucket = self._qos_buckets[(route.name, tenant)] = \
                    TokenBucket(rate, burst, now)
            return bucket.try_take(now)

    # -- auth ---------------------------------------------------------------

    def _authorized(self, handler: BaseHTTPRequestHandler) -> bool:
        if not self.auth_url:
            return True
        req = urllib.request.Request(self.auth_url, method="GET")
        cookie = handler.headers.get("Cookie")
        if cookie:
            req.add_header("Cookie", cookie)
        auth = handler.headers.get("Authorization")
        if auth:
            req.add_header("Authorization", auth)
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                return 200 <= resp.status < 300
        except urllib.error.HTTPError:
            return False
        except OSError:
            return False

    # -- proxy --------------------------------------------------------------

    def _probe_upstreams(self) -> None:
        """Active prober loop: every route backend (split variants AND
        single-backend services) gets a liveness probe per interval, so
        a dead upstream is ejected — and a recovered one readmitted via
        the half-open walk — without client traffic discovering it."""
        while not self._cert_watch_stop.wait(self.probe_interval):
            services: set[str] = set()
            for r in self.table.snapshot():
                services.add(r["service"])
                services.update(b[0] for b in r.get("backends", ()))
            try:
                self.health.probe(sorted(services), self.resolve)
            except Exception:  # pragma: no cover — probe must never die
                log.exception("upstream probe pass failed")

    def _watch_certs(self) -> None:
        """Poll the cert/key files; on change, reload them into the SAME
        SSLContext — new handshakes present the rotated certificate while
        the listener and every established connection stay up (the
        rotation contract the certificate controller relies on)."""
        import os

        def stamp():
            try:
                return (os.stat(self.certfile).st_mtime_ns,
                        os.stat(self.keyfile).st_mtime_ns
                        if self.keyfile else 0)
            except OSError:
                return None

        last = stamp()
        while not self._cert_watch_stop.wait(self.cert_reload_seconds):
            now = stamp()
            if now is None or now == last:
                continue
            try:
                self._ssl_ctx.load_cert_chain(self.certfile,
                                              self.keyfile or None)
                self.cert_reloads += 1
                last = now
            except (OSError, ValueError):
                # Mid-rotation read (cert/key momentarily mismatched):
                # keep serving the previous pair; next poll retries.
                pass

    def _make_redirect_handler(gw: "Gateway"):
        class Redirect(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _redirect(self):
                host = (self.headers.get("Host") or "").split(":")[0]
                if not host:
                    # No Host → no valid Location to build.
                    self.send_response(400)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                target = gw.redirect_target_port
                port = "" if target in (None, 443) else f":{target}"
                self.send_response(301)
                self.send_header("Location",
                                 f"https://{host}{port}{self.path}")
                self.send_header("Content-Length", "0")
                self.end_headers()

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _redirect

        return Redirect

    def start(self) -> None:
        self._proxy = ThreadingHTTPServer(
            ("0.0.0.0", self.port), make_proxy_handler(self)
        )
        self.port = self._proxy.server_address[1]  # resolve port 0
        if self.certfile:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(self.certfile,
                                          self.keyfile or None)
            self._proxy.socket = self._ssl_ctx.wrap_socket(
                self._proxy.socket, server_side=True
            )
            if self.cert_reload_seconds > 0:
                threading.Thread(target=self._watch_certs,
                                 daemon=True).start()
        threading.Thread(target=self._proxy.serve_forever,
                         daemon=True).start()
        if self.redirect_port is not None:
            self._redirect = ThreadingHTTPServer(
                ("0.0.0.0", self.redirect_port),
                self._make_redirect_handler(),
            )
            self.redirect_port = self._redirect.server_address[1]
            threading.Thread(target=self._redirect.serve_forever,
                             daemon=True).start()
        if self.admin_port:
            self._admin = ThreadingHTTPServer(
                ("0.0.0.0", self.admin_port), make_admin_handler(self)
            )
            threading.Thread(target=self._admin.serve_forever,
                             daemon=True).start()
        if self.probe_interval > 0:
            threading.Thread(target=self._probe_upstreams,
                             daemon=True).start()

    def stop(self) -> None:
        self._cert_watch_stop.set()
        for httpd in (self._proxy, self._admin, self._redirect):
            if httpd:
                httpd.shutdown()
