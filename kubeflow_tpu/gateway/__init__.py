"""API gateway: annotation-discovered reverse proxy.

The ambassador analogue (kubeflow/common/ambassador.libsonnet:7-226): every
platform Service that wants routing carries a
`kubeflow-tpu.org/gateway-route` annotation (the `getambassador.io/config`
pattern — route spec {name, prefix, service, rewrite}); the gateway watches
Services, keeps a longest-prefix route table, and proxies requests to the
backing service. Optional forward-auth: every request is checked against the
gatekeeper's /auth endpoint first (the IAP/basic-auth ingress role,
kubeflow/common/basic-auth.libsonnet).

Proxying is streaming end to end: response bodies are relayed chunk by
chunk as the upstream produces them (chunked re-encoding when the upstream
length is unknown — SSE/token streams flow unbuffered), and an HTTP/1.1
Upgrade handshake (notebooks' websocket kernels,
kubeflow/jupyter/jupyter.libsonnet:97-106 `use_websocket: true`) switches
the connection to a transparent bidirectional TCP tunnel.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

import yaml

from kubeflow_tpu.k8s.client import K8sClient
from kubeflow_tpu.manifests.core import GATEWAY_ROUTE_ANNOTATION

log = logging.getLogger(__name__)

# Hop-by-hop headers never forwarded (RFC 7230 §6.1).
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailers", "transfer-encoding", "upgrade",
    "host", "content-length",
}


@dataclass(frozen=True)
class Route:
    name: str
    prefix: str
    service: str  # host:port (the primary backend)
    rewrite: str = "/"
    # Traffic splitting (the seldon abtest/mab/canary surface,
    # /root/reference/kubeflow/seldon/prototypes, core.libsonnet:305):
    # weighted variants — each request is routed to one backend drawn by
    # weight. Empty = all traffic to `service`.
    backends: tuple = ()  # ((host:port, weight), ...)
    # "weighted": static draw by weight. "epsilon-greedy": the seldon
    # multi-armed-bandit router (epsilon-greedy prototype) — explore a
    # random variant with probability epsilon, otherwise exploit the
    # best observed reward; rewards come from response status (5xx/
    # connect-fail = 0) or the admin feedback endpoint.
    strategy: str = "weighted"
    epsilon: float = 0.1
    # Shadow/mirror target: every request is also sent fire-and-forget to
    # this backend; its response is discarded and its failures invisible.
    shadow: str = ""
    # Outlier detection (seldon outlier-detector-v1alpha2 surface): score
    # each prediction request's feature against a running window;
    # |z| > threshold tags the response and counts into the outlier rate.
    # 0 disables.
    outlier_threshold: float = 0.0
    outlier_window: int = 100
    # Identity-token policy for this route: "" = the gateway default
    # (verify when a JwtVerifier is configured), "off" = this route is
    # exempt (the per-route face of iap.libsonnet:600's bypass_jwt).
    jwt: str = ""

    def pick_service(self, rng) -> str:
        if not self.backends:
            return self.service
        services = [b[0] for b in self.backends]
        weights = [b[1] for b in self.backends]
        return rng.choices(services, weights=weights)[0]

    def target_for(self, path: str, service: str | None = None) -> str:
        """Rewrite `path` (which startswith prefix) onto the backend."""
        rest = path[len(self.prefix):]
        base = (self.rewrite if self.rewrite.endswith("/")
                else self.rewrite + "/")
        return ("http://" + (service or self.service) + base
                + rest.lstrip("/"))


class OutlierStats:
    """Route-attached anomaly scoring — the seldon outlier-detector
    variant (/root/reference/kubeflow/seldon/prototypes/
    outlier-detector-v1alpha2.jsonnet:1-128 attaches a Mahalanobis
    scorer to a model route). Platform recast: a running z-score over a
    scalar feature of each prediction request (mean |value| of the
    instances payload), maintained per route over a sliding window.
    Requests scoring beyond the route's threshold are tagged
    (X-Outlier/X-Outlier-Score response headers — the streamed relay
    never buffers bodies, so tagging rides headers) and counted into the
    outlier-rate metric."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # route -> (window deque, outliers, scored)
        self._windows: dict[str, object] = {}
        self._counts: dict[str, list[int]] = {}

    @staticmethod
    def feature(body: bytes | None) -> float | None:
        """Scalar feature of a prediction request: mean |x| over every
        numeric leaf of "instances". None = not scoreable (no/bad JSON,
        no numerics) — never an error, scoring must not break proxying."""
        if not body:
            return None
        try:
            payload = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None
        total, n = 0.0, 0
        stack = [payload.get("instances")
                 if isinstance(payload, dict) else payload]
        while stack:
            node = stack.pop()
            if isinstance(node, bool):
                continue
            if isinstance(node, (int, float)):
                total += abs(float(node))
                n += 1
            elif isinstance(node, list):
                stack.extend(node)
            elif isinstance(node, dict):
                stack.extend(node.values())
        return total / n if n else None

    # Baseline points required before anything is flagged: a 2-sample
    # window's std is noise, and normal jitter would score "infinite".
    WARMUP = 10

    def score(self, route: str, value: float, *, window: int,
              threshold: float) -> tuple[float, bool]:
        """Running z-score of ``value`` against the route's window
        (scored BEFORE insertion, so one huge request can't mask
        itself); returns (score, is_outlier). Warmup requests build the
        baseline and are never flagged."""
        import collections
        import math

        with self._lock:
            win = self._windows.setdefault(
                route, collections.deque(maxlen=max(window, 2))
            )
            counts = self._counts.setdefault(route, [0, 0])
            if win.maxlen != max(window, 2):
                # Window reconfigured (annotation re-applied): carry the
                # most recent baseline into the new size.
                win = collections.deque(win, maxlen=max(window, 2))
                self._windows[route] = win
            warm = len(win) >= min(self.WARMUP, win.maxlen)
            if len(win) >= 2:
                mean = sum(win) / len(win)
                var = sum((v - mean) ** 2 for v in win) / len(win)
                std = math.sqrt(var)
                z = abs(value - mean) / std if std > 1e-12 else (
                    0.0 if abs(value - mean) < 1e-12 else float("inf")
                )
            else:
                z = 0.0
            outlier = warm and z > threshold
            counts[1] += 1
            if outlier:
                counts[0] += 1
            else:
                # Outliers are excluded from the baseline, or a burst of
                # them would normalize itself into "normal".
                win.append(value)
            return (round(z, 4) if z != float("inf") else z, outlier)

    def snapshot(self, route: str) -> dict:
        with self._lock:
            outliers, scored = self._counts.get(route, (0, 0))
            return {"outliers": outliers, "scored": scored,
                    "rate": round(outliers / scored, 4) if scored else 0.0}

    def totals(self) -> tuple[int, int]:
        with self._lock:
            return (sum(c[0] for c in self._counts.values()),
                    sum(c[1] for c in self._counts.values()))


class UpstreamHealth:
    """Per-backend health with circuit breaking (the envoy outlier-
    detection role ambassador delegates to envoy; this platform's front
    door implements it natively):

    - passive observation: every proxied request records success/failure
      (connect errors and 5xx); ``failure_threshold`` consecutive
      failures EJECT the backend from every route's pick set for
      ``ejection_seconds``;
    - half-open recovery: after the ejection window one trial request is
      let through — success closes the circuit, failure re-ejects with
      doubled backoff (capped 10×);
    - active probes: a prober thread TCP-connects each known backend
      every ``probe_interval`` seconds so an upstream that died between
      requests is ejected (and a recovered one readmitted) without
      client traffic paying for the discovery.
    """

    def __init__(self, *, failure_threshold: int = 3,
                 ejection_seconds: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = failure_threshold
        self.ejection_seconds = ejection_seconds
        self.clock = clock
        self._lock = threading.Lock()
        # service -> {fails, ejected_until, ejections, state-extras}
        self._state: dict[str, dict] = {}

    def _cell(self, service: str) -> dict:
        return self._state.setdefault(service, {
            "consecutive_failures": 0, "ejected_until": 0.0,
            "ejections": 0, "half_open_inflight": False,
            "trial_started": 0.0, "last_change": self.clock(),
        })

    def record_success(self, service: str) -> None:
        with self._lock:
            cell = self._cell(service)
            recovered = (cell["consecutive_failures"]
                         >= self.failure_threshold)
            cell.update(consecutive_failures=0, ejected_until=0.0,
                        half_open_inflight=False)
            if recovered:
                cell.update(ejections=0, last_change=self.clock())

    # A half-open trial that never reported back (e.g. the request rode
    # an upgrade tunnel, which doesn't record outcomes) expires so the
    # backend isn't stuck "trial in flight" forever.
    TRIAL_TIMEOUT = 30.0

    def record_failure(self, service: str) -> None:
        with self._lock:
            cell = self._cell(service)
            cell["consecutive_failures"] += 1
            cell["half_open_inflight"] = False
            if cell["consecutive_failures"] >= self.failure_threshold:
                # Re-eject with doubled backoff per consecutive ejection
                # (half-open trial failed), capped at 10x — exponent
                # clamped so a long-dead backend can't grow a bigint.
                backoff = self.ejection_seconds * min(
                    2 ** min(cell["ejections"], 4), 10
                )
                cell["ejected_until"] = self.clock() + backoff
                cell["ejections"] += 1
                cell["last_change"] = self.clock()

    def _eligible_locked(self, cell: dict | None) -> bool:
        if cell is None or cell["consecutive_failures"] \
                < self.failure_threshold:
            return True
        if self.clock() < cell["ejected_until"]:
            return False
        if cell["half_open_inflight"] and (
                self.clock() - cell["trial_started"] < self.TRIAL_TIMEOUT):
            return False
        return True  # window elapsed: a trial may begin

    def admits(self, service: str) -> bool:
        """Side-effect-free eligibility: healthy, or ejection window
        elapsed with no trial in flight."""
        with self._lock:
            return self._eligible_locked(self._state.get(service))

    def begin_trial(self, service: str) -> None:
        """Mark the half-open trial as in flight for the backend a
        request was ACTUALLY routed to (never during pick-set filtering —
        an unpicked backend must not have its one trial consumed)."""
        with self._lock:
            cell = self._state.get(service)
            if (cell is not None
                    and cell["consecutive_failures"]
                    >= self.failure_threshold
                    and self.clock() >= cell["ejected_until"]):
                cell["half_open_inflight"] = True
                cell["trial_started"] = self.clock()

    def filter_healthy(self, services: list[str]) -> list[str]:
        """The pick set: ejected backends drop out; if EVERYTHING is
        ejected, fail open with the full set (a wrong 502 beats
        blackholing when the health data itself is suspect)."""
        healthy = [s for s in services if self.admits(s)]
        return healthy or list(services)

    def probe(self, services: list[str],
              resolve: Callable[[str], str]) -> None:
        """Active TCP-connect probe of every service (cheap, protocol-
        agnostic — the readiness signal is 'something is listening')."""
        for service in services:
            addr = resolve(service)
            host, _, port_s = addr.partition(":")
            try:
                with socket.create_connection(
                        (host, int(port_s or 80)), timeout=2.0):
                    pass
                self.record_success(service)
            except OSError:
                self.record_failure(service)

    def snapshot(self) -> dict:
        with self._lock:
            now = self.clock()
            return {
                svc: {
                    "healthy": cell["consecutive_failures"]
                    < self.failure_threshold,
                    "consecutive_failures": cell["consecutive_failures"],
                    "ejected_for_seconds": round(
                        max(0.0, cell["ejected_until"] - now), 2),
                    "ejections": cell["ejections"],
                }
                for svc, cell in self._state.items()
            }


class BanditStats:
    """Per-(route, backend) reward averages for epsilon-greedy routes."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: dict[tuple[str, str], list[float]] = {}

    def record(self, route: str, service: str, reward: float) -> None:
        with self._lock:
            cell = self._stats.setdefault((route, service), [0.0, 0])
            cell[0] += reward
            cell[1] += 1

    def pick(self, route: Route, rng, services: list[str] | None = None
             ) -> str:
        """Explore uniformly with prob epsilon; otherwise exploit the best
        mean reward. Untried backends are optimistic (mean 1.0), so every
        variant gets traffic before exploitation locks in. ``services``
        restricts the arms (the health layer's ejection filter)."""
        if services is None:
            services = [b[0] for b in route.backends]
        if rng.random() < route.epsilon:
            return rng.choice(services)
        with self._lock:
            def mean(svc: str) -> float:
                total, n = self._stats.get((route.name, svc), (0.0, 0))
                return total / n if n else 1.0

            best = max(mean(s) for s in services)
            top = [s for s in services if mean(s) == best]
        return rng.choice(top)

    def snapshot(self, route_name: str) -> dict:
        with self._lock:
            return {
                svc: {"reward_sum": round(total, 4), "trials": n,
                      "mean": round(total / n, 4) if n else None}
                for (rname, svc), (total, n) in self._stats.items()
                if rname == route_name
            }


def routes_from_service(svc: dict) -> list[Route]:
    raw = svc.get("metadata", {}).get("annotations", {}).get(
        GATEWAY_ROUTE_ANNOTATION
    )
    if not raw:
        return []
    try:
        specs = yaml.safe_load(raw)
    except yaml.YAMLError:
        log.warning("bad route annotation on %s",
                    svc["metadata"].get("name"))
        return []
    if isinstance(specs, dict):
        specs = [specs]
    routes = []
    for spec in specs or []:
        try:
            backends = tuple(
                (b["service"], float(b.get("weight", 1)))
                for b in spec.get("backends", [])
            )
            if backends and any(w < 0 for _s, w in backends):
                raise ValueError("negative backend weight")
            if backends and not any(w > 0 for _s, w in backends):
                raise ValueError("all backend weights zero")
            service = spec.get("service") or (
                backends[0][0] if backends else None
            )
            if not service:
                raise KeyError("service")
            strategy = spec.get("strategy", "weighted")
            if strategy not in ("weighted", "epsilon-greedy"):
                raise ValueError(f"unknown strategy {strategy!r}")
            epsilon = float(spec.get("epsilon", 0.1))
            if not 0.0 <= epsilon <= 1.0:
                raise ValueError("epsilon must be in [0, 1]")
            outlier = spec.get("outlier", {}) or {}
            outlier_threshold = float(outlier.get("threshold", 0.0))
            outlier_window = int(outlier.get("window", 100))
            if outlier_threshold < 0:
                raise ValueError("outlier threshold must be >= 0")
            if outlier_window < 2:
                raise ValueError("outlier window must be >= 2")
            jwt = str(spec.get("jwt", ""))
            if jwt not in ("", "off", "required"):
                raise ValueError(f"jwt must be 'off' or 'required', "
                                 f"got {jwt!r}")
            routes.append(Route(
                jwt=jwt,
                name=spec["name"], prefix=spec["prefix"],
                service=service, rewrite=spec.get("rewrite", "/"),
                backends=backends, strategy=strategy, epsilon=epsilon,
                shadow=spec.get("shadow", ""),
                outlier_threshold=outlier_threshold,
                outlier_window=outlier_window,
            ))
        except (KeyError, TypeError, ValueError) as e:
            log.warning("bad route spec in %s: %s",
                        svc["metadata"].get("name"), e)
    return routes


class RouteTable:
    """Longest-prefix route lookup, refreshed from Service annotations."""

    def __init__(self) -> None:
        self._routes: list[Route] = []
        self._lock = threading.Lock()

    def set_routes(self, routes: list[Route]) -> None:
        with self._lock:
            # Longest prefix first; on equal prefixes a split/shadow route
            # beats a plain one (a serving-route canary for a model must
            # override the model Service's own direct route, not lose the
            # tie to listing order), then name for determinism.
            self._routes = sorted(
                routes,
                key=lambda r: (-len(r.prefix),
                               0 if (r.backends or r.shadow) else 1,
                               r.name),
            )

    def refresh(self, client: K8sClient, namespace: str | None = None) -> int:
        routes = []
        for svc in client.list("v1", "Service", namespace):
            routes.extend(routes_from_service(svc))
        self.set_routes(routes)
        return len(routes)

    def match(self, path: str) -> Route | None:
        with self._lock:
            for r in self._routes:
                if path.startswith(r.prefix):
                    return r
        return None

    def snapshot(self) -> list[dict]:
        with self._lock:
            # Copies, not the live __dict__ of the frozen Routes — callers
            # (the admin handler) annotate these per request.
            return [dict(vars(r)) for r in self._routes]

    def find(self, name: str) -> Route | None:
        with self._lock:
            return next((r for r in self._routes if r.name == name), None)


class Gateway:
    """The proxy + admin servers.

    ``resolve`` maps a route's `host:port` service address to the address to
    actually dial — identity in-cluster, overridden in tests to point at
    local fixture backends.
    """

    def __init__(
        self,
        table: RouteTable,
        *,
        port: int = 8080,
        admin_port: int = 8877,
        auth_url: str = "",
        resolve: Callable[[str], str] | None = None,
        certfile: str = "",
        keyfile: str = "",
        cert_reload_seconds: float = 5.0,
        redirect_port: int | None = None,
        redirect_target_port: int | None = None,
        challenge_lookup: Callable[[str], str | None] | None = None,
        upstream_timeout: float = 60.0,
        health: UpstreamHealth | None = None,
        probe_interval: float = 2.0,
        retry_budget: float = 0.2,
        jwt_verifier=None,
        rng=None,
    ):
        self.table = table
        self.port = port
        self.admin_port = admin_port
        self.auth_url = auth_url
        self.resolve = resolve or (lambda addr: addr)
        self.upstream_timeout = upstream_timeout
        # TLS termination at the gateway (the iap-ingress/cert-manager
        # role, kubeflow/gcp/iap.libsonnet): cert+key mounted from a
        # Secret; empty = plain HTTP (in-mesh or behind an LB). The
        # mounted files are WATCHED: when the certificate controller
        # rotates the secret, new handshakes pick up the new cert from
        # the same SSLContext without dropping the listener or any
        # established connection (cert_reload_seconds poll; 0 disables).
        self.certfile = certfile
        self.keyfile = keyfile
        self.cert_reload_seconds = cert_reload_seconds
        # components/https-redirect analogue: a plain-HTTP listener that
        # 301s every request to the HTTPS entrypoint. None = disabled.
        # ``redirect_target_port`` is the EXTERNALLY advertised HTTPS port
        # (None = omit, the :443 default) — behind a Service mapping
        # 443→bind-port, the bind port must never leak into Location.
        self.redirect_port = redirect_port
        self.redirect_target_port = redirect_target_port
        # ACME HTTP-01: serves /.well-known/acme-challenge/<token> from
        # the certificate controller's published challenges (the path a
        # letsencrypt-style validator fetches pre-issuance).
        self.challenge_lookup = challenge_lookup
        self.cert_reloads = 0
        # Weight-draw source for traffic splitting (seedable in tests).
        self.rng = rng or random.Random()
        # Reward averages for epsilon-greedy (bandit) routes.
        self.bandit = BanditStats()
        # Per-route anomaly scoring (seldon outlier-detector surface).
        self.outliers = OutlierStats()
        # Upstream health/circuit breaking: passive per-request
        # observations + an active prober thread (probe_interval; 0
        # disables the prober, passive observation still applies).
        self.health = health or UpstreamHealth()
        self.probe_interval = probe_interval
        # Idempotent-retry budget (envoy-style): GET/HEAD requests that
        # hit a dead backend may retry ONCE against a different healthy
        # backend, as long as retries stay under this fraction of
        # requests — a hard cap so retries can't amplify an outage.
        self.retry_budget = retry_budget
        # Identity-token verification (gateway/jwt_auth.JwtVerifier) —
        # the envoy jwt-auth filter role (iap.libsonnet:589-600). None =
        # no bearer-token requirement. When BOTH a verifier and a
        # forward-auth URL are configured, a request passes with EITHER a
        # valid token OR a valid session (IAP's browser-login + SA
        # id-token duality).
        self.jwt_verifier = jwt_verifier
        self.retries_total = 0
        self.requests_total = 0
        self.errors_total = 0
        self.tunnels_total = 0
        self.shadow_total = 0
        self._proxy: ThreadingHTTPServer | None = None
        self._admin: ThreadingHTTPServer | None = None
        self._redirect: ThreadingHTTPServer | None = None
        self._ssl_ctx = None
        self._cert_watch_stop = threading.Event()

    def _retry_allowed(self) -> bool:
        return (self.retries_total + 1) <= self.retry_budget * max(
            self.requests_total, 1
        )

    # -- auth ---------------------------------------------------------------

    def _authorized(self, handler: BaseHTTPRequestHandler) -> bool:
        if not self.auth_url:
            return True
        req = urllib.request.Request(self.auth_url, method="GET")
        cookie = handler.headers.get("Cookie")
        if cookie:
            req.add_header("Cookie", cookie)
        auth = handler.headers.get("Authorization")
        if auth:
            req.add_header("Authorization", auth)
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                return 200 <= resp.status < 300
        except urllib.error.HTTPError:
            return False
        except OSError:
            return False

    # -- proxy --------------------------------------------------------------

    def _make_proxy_handler(gw: "Gateway"):
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _respond(self, code: int, body: bytes,
                         headers: dict | None = None) -> None:
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                if headers is None or "Content-Type" not in headers:
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":  # RFC 7231: HEAD has no body
                    self.wfile.write(body)

            def _handle(self):
                gw.requests_total += 1
                if self.path == "/healthz":
                    self._respond(200, b'{"status":"ok"}')
                    return
                if self.path.startswith("/.well-known/acme-challenge/"):
                    token = self.path.rsplit("/", 1)[1]
                    body = (gw.challenge_lookup(token)
                            if gw.challenge_lookup else None)
                    if body is None:
                        self._respond(404, b'{"error":"unknown challenge"}')
                    else:
                        self._respond(200, body.encode(),
                                      {"Content-Type": "text/plain"})
                    return
                route = gw.table.match(self.path)
                if route is None:
                    gw.errors_total += 1
                    self._respond(
                        404,
                        json.dumps({"error": f"no route for {self.path}"})
                        .encode(),
                    )
                    return
                self._identity = None
                if route.jwt == "required" and gw.jwt_verifier is None:
                    # Fail CLOSED: an operator demanded token checks on
                    # this route but the gateway has no verifier — a
                    # misconfiguration must not silently serve open.
                    gw.errors_total += 1
                    self._respond(503, json.dumps(
                        {"error": "route requires jwt but the gateway "
                                  "has no verifier configured"}).encode())
                    return
                if gw.jwt_verifier is not None and route.jwt != "off":
                    claims, reason = gw.jwt_verifier.check(
                        self.command, self.path, self.headers
                    )
                    if claims is None:
                        # Browser sessions may still pass through
                        # forward-auth when it is configured (IAP serves
                        # both logins and SA id-tokens) — unless the
                        # route pins jwt: "required", which accepts
                        # nothing but a valid bearer token.
                        session_ok = (route.jwt != "required"
                                      and gw.auth_url
                                      and gw._authorized(self))
                        if not session_ok:
                            self._respond(401, json.dumps(
                                {"error": "unauthorized", "reason": reason}
                            ).encode(), {
                                "WWW-Authenticate":
                                    f'Bearer error="{reason}"',
                                "Content-Type": "application/json",
                            })
                            return
                    elif claims.get("sub"):
                        self._identity = str(claims["sub"])
                elif not gw._authorized(self):
                    self._respond(
                        401, json.dumps({"error": "unauthorized",
                                         "login": "/login"}).encode(),
                    )
                    return
                service = self._pick_backend(route)
                target = route.target_for(self.path, service)
                # Re-point at the resolved backend address.
                target = target.replace(service, gw.resolve(service), 1)
                parts = urllib.parse.urlsplit(target)
                backend_path = parts.path + (
                    "?" + parts.query if parts.query else ""
                )
                if self._is_upgrade():
                    self._tunnel(route, parts.hostname, parts.port,
                                 backend_path)
                    return
                self._proxy_http(route, parts.hostname, parts.port,
                                 backend_path, service)

            def _pick_backend(self, route, exclude: str | None = None
                              ) -> str:
                """Choose a backend with ejected upstreams filtered out of
                the pick set (weighted draws AND bandit arms); ``exclude``
                additionally drops the backend a retry just failed on."""
                if not route.backends:
                    return route.service  # nowhere else to go
                services = gw.health.filter_healthy(
                    [b[0] for b in route.backends]
                )
                if exclude and len(services) > 1:
                    services = [s for s in services if s != exclude]
                if route.strategy == "epsilon-greedy":
                    picked = gw.bandit.pick(route, gw.rng, services)
                else:
                    weights = {b[0]: b[1] for b in route.backends}
                    draw = [weights[s] for s in services]
                    if not any(draw):  # only zero-weight backends left
                        draw = [1.0] * len(services)
                    picked = gw.rng.choices(services, weights=draw)[0]
                # Consume the half-open trial only on the backend that
                # actually takes the request.
                gw.health.begin_trial(picked)
                return picked

            def _is_upgrade(self) -> bool:
                conn_tokens = [
                    t.strip().lower()
                    for t in self.headers.get("Connection", "").split(",")
                ]
                return ("upgrade" in conn_tokens
                        and bool(self.headers.get("Upgrade")))

            # -- plain HTTP: streamed relay -----------------------------

            def _proxy_http(self, route, host, port, path, service=None,
                            is_retry=False):
                # On a retry the request body stream is already consumed —
                # only bodyless idempotent methods reach here retrying.
                length = (0 if is_retry
                          else int(self.headers.get("Content-Length", 0)))
                body = self.rfile.read(length) if length else None
                # Forwarded prefix and authenticated identity are
                # gateway-asserted — client-supplied copies must never
                # reach the backend (spoofing).
                headers = {
                    k: v for k, v in self.headers.items()
                    if k.lower() not in _HOP_HEADERS
                    and k.lower() not in ("x-forwarded-prefix",
                                          "x-auth-identity")
                }
                headers["X-Forwarded-Prefix"] = route.prefix
                if getattr(self, "_identity", None):
                    # The x-goog-authenticated-user-email analogue.
                    headers["X-Auth-Identity"] = self._identity
                if route.shadow and not is_retry:
                    self._mirror(route, path, body, dict(headers))
                tag_headers = {}
                if route.outlier_threshold > 0 and not is_retry:
                    value = OutlierStats.feature(body)
                    if value is not None:
                        z, is_out = gw.outliers.score(
                            route.name, value,
                            window=route.outlier_window,
                            threshold=route.outlier_threshold,
                        )
                        tag_headers = {
                            "X-Outlier": "true" if is_out else "false",
                            "X-Outlier-Score": str(z),
                        }
                bandit = (route.strategy == "epsilon-greedy"
                          and service is not None)
                conn = HTTPConnection(host, port,
                                      timeout=gw.upstream_timeout)
                try:
                    try:
                        self._connect_upstream(conn)
                        conn.request(self.command, path, body=body,
                                     headers=headers)
                        resp = conn.getresponse()
                    except OSError as e:
                        if bandit:
                            gw.bandit.record(route.name, service, 0.0)
                        if service is not None:
                            gw.health.record_failure(service)
                        # Idempotent-GET retry: one shot at a DIFFERENT
                        # healthy backend, under the retry budget (a
                        # connect failure never duplicated a request).
                        if (self.command in ("GET", "HEAD")
                                and not is_retry
                                and route.backends
                                and service is not None
                                and gw._retry_allowed()):
                            retry_to = self._pick_backend(
                                route, exclude=service)
                            if retry_to != service:
                                gw.retries_total += 1
                                r_target = route.target_for(
                                    self.path, retry_to)
                                r_target = r_target.replace(
                                    retry_to, gw.resolve(retry_to), 1)
                                p = urllib.parse.urlsplit(r_target)
                                self._proxy_http(
                                    route, p.hostname, p.port,
                                    p.path + ("?" + p.query
                                              if p.query else ""),
                                    retry_to, is_retry=True,
                                )
                                return
                        gw.errors_total += 1
                        self._respond(
                            502,
                            json.dumps(
                                {"error": f"upstream {host}:{port}: {e}"}
                            ).encode(),
                        )
                        return
                    if bandit:
                        # Implicit reward: server errors are failures.
                        gw.bandit.record(route.name, service,
                                         0.0 if resp.status >= 500 else 1.0)
                    if service is not None:
                        # Passive health observation: 5xx counts against
                        # the upstream; anything else closes its circuit.
                        if resp.status >= 500:
                            gw.health.record_failure(service)
                        else:
                            gw.health.record_success(service)
                    self._relay_response(resp, tag_headers)
                finally:
                    conn.close()

            def _mirror(self, route, path, body, headers):
                """Fire-and-forget request mirror (seldon shadow/outlier
                surface): the shadow backend sees live traffic, its
                response is discarded, its failures never touch the
                client."""
                addr = gw.resolve(route.shadow)
                host, _, port_s = addr.partition(":")
                method = self.command
                headers["X-Shadow"] = "true"

                def send():
                    gw.shadow_total += 1
                    try:
                        conn = HTTPConnection(
                            host, int(port_s or 80),
                            timeout=gw.upstream_timeout,
                        )
                        conn.request(method, path, body=body,
                                     headers=headers)
                        conn.getresponse().read()
                        conn.close()
                    except (OSError, ValueError):
                        pass

                threading.Thread(target=send, daemon=True).start()

            def _connect_upstream(self, conn):
                """Connect with one retry — connect-phase only, so an
                in-flight request is never duplicated against a slow but
                alive upstream (ksonnet.go:147-168's retry role at the
                connection level)."""
                try:
                    conn.connect()
                except OSError:
                    conn.close()
                    time.sleep(0.1)
                    conn.connect()

            def _relay_response(self, resp, extra_headers=None):
                try:
                    self.send_response(resp.status)
                    for k, v in resp.getheaders():
                        if k.lower() not in _HOP_HEADERS:
                            self.send_header(k, v)
                    for k, v in (extra_headers or {}).items():
                        self.send_header(k, v)
                    upstream_len = resp.getheader("Content-Length")
                    bodyless = (self.command == "HEAD"
                                or resp.status in (204, 304)
                                or 100 <= resp.status < 200)
                    if bodyless or upstream_len is not None:
                        if upstream_len is not None:
                            self.send_header("Content-Length", upstream_len)
                        self.end_headers()
                        if not bodyless:
                            self._relay_known_length(resp,
                                                     int(upstream_len))
                    else:
                        self._relay_stream(resp)
                    self.wfile.flush()
                except OSError:
                    # Mid-stream failure: the status line is already gone;
                    # drop the connection rather than corrupt the body.
                    gw.errors_total += 1
                    self.close_connection = True

            def _relay_known_length(self, resp, remaining: int) -> None:
                while remaining > 0:
                    data = resp.read(min(65536, remaining))
                    if not data:
                        # Upstream died short of its advertised length;
                        # the client was promised more bytes — drop the
                        # connection so it can't desync on a reuse.
                        gw.errors_total += 1
                        self.close_connection = True
                        return
                    self.wfile.write(data)
                    remaining -= len(data)

            def _relay_stream(self, resp) -> None:
                """Unknown upstream length (chunked/EOF-delimited):
                re-chunk and flush as data arrives so streaming bodies
                (SSE, token streams) are never buffered. HTTP/1.0 clients
                can't parse chunked — stream raw and close."""
                chunked = self.request_version != "HTTP/1.0"
                if chunked:
                    self.send_header("Transfer-Encoding", "chunked")
                else:
                    self.close_connection = True
                self.end_headers()
                while True:
                    data = resp.read1(65536)
                    if not data:
                        break
                    if chunked:
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode() + data + b"\r\n"
                        )
                    else:
                        self.wfile.write(data)
                    self.wfile.flush()
                if chunked:
                    self.wfile.write(b"0\r\n\r\n")

            # -- HTTP/1.1 Upgrade: transparent TCP tunnel ---------------

            def _tunnel(self, route, host, port, path):
                """Forward the Upgrade handshake verbatim and then pump
                bytes both ways — the websocket path notebooks need
                (jupyter.libsonnet:97-106). The gateway never parses
                frames; after the handshake it is a plain TCP relay, so
                the backend's 101 (or its refusal) reaches the client
                unmodified."""
                try:
                    backend = socket.create_connection(
                        (host, port), timeout=gw.upstream_timeout
                    )
                except OSError as e:
                    gw.errors_total += 1
                    self._respond(
                        502,
                        json.dumps(
                            {"error": f"upstream {host}:{port}: {e}"}
                        ).encode(),
                    )
                    return
                gw.tunnels_total += 1
                lines = [f"{self.command} {path} HTTP/1.1",
                         f"Host: {host}:{port}",
                         f"X-Forwarded-Prefix: {route.prefix}"]
                if getattr(self, "_identity", None):
                    lines.append(f"X-Auth-Identity: {self._identity}")
                # Hop-by-hop headers are the handshake here — forward
                # everything except Host (rewritten above) and the
                # gateway-asserted headers (spoofing).
                lines += [
                    f"{k}: {v}" for k, v in self.headers.items()
                    if k.lower() not in ("host", "x-forwarded-prefix",
                                         "x-auth-identity")
                ]
                try:
                    backend.sendall(
                        ("\r\n".join(lines) + "\r\n\r\n").encode()
                    )
                    # Tunnel sockets outlive the 60s request timeout.
                    backend.settimeout(None)
                    self.connection.settimeout(None)
                    done = threading.Event()

                    def pump(read, write):
                        try:
                            while True:
                                data = read(65536)
                                if not data:
                                    break
                                write(data)
                        except (OSError, ValueError):
                            pass
                        finally:
                            done.set()

                    def write_client(data):
                        self.wfile.write(data)
                        self.wfile.flush()

                    for read, write in (
                        (self.rfile.read1, backend.sendall),
                        (backend.recv, write_client),
                    ):
                        threading.Thread(target=pump, args=(read, write),
                                         daemon=True).start()
                    # First direction to close ends the tunnel; the
                    # shutdown below unblocks the other pump.
                    done.wait()
                finally:
                    for s in (backend, self.connection):
                        try:
                            s.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                    backend.close()
                    self.close_connection = True

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _handle
            do_HEAD = do_OPTIONS = _handle

        return Handler

    def _make_admin_handler(gw: "Gateway"):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/routes":
                    routes = gw.table.snapshot()
                    for r in routes:
                        if r.get("strategy") == "epsilon-greedy":
                            r["bandit"] = gw.bandit.snapshot(r["name"])
                        if r.get("outlier_threshold"):
                            r["outliers"] = gw.outliers.snapshot(r["name"])
                    body = json.dumps(routes).encode()
                    ctype = "application/json"
                elif self.path == "/upstreams":
                    # Upstream health + circuit state, per backend (the
                    # envoy clusters/outlier admin surface).
                    body = json.dumps(gw.health.snapshot()).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    body = (
                        "# TYPE gateway_requests_total counter\n"
                        f"gateway_requests_total {gw.requests_total}\n"
                        "# TYPE gateway_errors_total counter\n"
                        f"gateway_errors_total {gw.errors_total}\n"
                        "# TYPE gateway_upgrade_tunnels_total counter\n"
                        f"gateway_upgrade_tunnels_total {gw.tunnels_total}\n"
                        "# TYPE gateway_shadow_requests_total counter\n"
                        f"gateway_shadow_requests_total {gw.shadow_total}\n"
                        "# TYPE gateway_retries_total counter\n"
                        f"gateway_retries_total {gw.retries_total}\n"
                        "# TYPE gateway_outliers_total counter\n"
                        f"gateway_outliers_total {gw.outliers.totals()[0]}\n"
                        "# TYPE gateway_outlier_scored_total counter\n"
                        "gateway_outlier_scored_total "
                        f"{gw.outliers.totals()[1]}\n"
                        "# TYPE gateway_jwt_verified_total counter\n"
                        "gateway_jwt_verified_total "
                        f"{getattr(gw.jwt_verifier, 'verified_total', 0)}\n"
                        "# TYPE gateway_jwt_rejected_total counter\n"
                        "gateway_jwt_rejected_total "
                        f"{getattr(gw.jwt_verifier, 'rejected_total', 0)}\n"
                    ).encode()
                    ctype = "text/plain"
                elif self.path in ("/healthz", "/readyz"):
                    body, ctype = b'{"status":"ok"}', "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                """POST /routes/<name>/feedback {"service", "reward"} —
                the seldon /send-feedback analogue: callers grade a
                variant's answer (0..1) after the fact, steering the
                epsilon-greedy router beyond what status codes reveal."""
                parts = self.path.strip("/").split("/")
                if (len(parts) != 3 or parts[0] != "routes"
                        or parts[2] != "feedback"):
                    self.send_response(404)
                    self.end_headers()
                    return
                route = gw.table.find(parts[1])
                if route is None:
                    body = json.dumps(
                        {"error": f"no route {parts[1]!r}"}).encode()
                    self.send_response(404)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    service = payload["service"]
                    reward = float(payload["reward"])
                    if not 0.0 <= reward <= 1.0:
                        raise ValueError("reward must be in [0, 1]")
                    # Only the route's real variants are gradeable — a
                    # typo'd service must not 200-and-steer-nothing, and
                    # validation bounds the stats table to routes×backends.
                    variants = {b[0] for b in route.backends}
                    if service not in variants:
                        raise ValueError(
                            f"service {service!r} is not a variant of "
                            f"route {parts[1]!r}")
                except (ValueError, KeyError, TypeError) as e:
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                gw.bandit.record(parts[1], service, reward)
                body = json.dumps(
                    {"ok": True,
                     "stats": gw.bandit.snapshot(parts[1])}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        return Handler

    def _probe_upstreams(self) -> None:
        """Active prober loop: every route backend (split variants AND
        single-backend services) gets a liveness probe per interval, so
        a dead upstream is ejected — and a recovered one readmitted via
        the half-open walk — without client traffic discovering it."""
        while not self._cert_watch_stop.wait(self.probe_interval):
            services: set[str] = set()
            for r in self.table.snapshot():
                services.add(r["service"])
                services.update(b[0] for b in r.get("backends", ()))
            try:
                self.health.probe(sorted(services), self.resolve)
            except Exception:  # pragma: no cover — probe must never die
                log.exception("upstream probe pass failed")

    def _watch_certs(self) -> None:
        """Poll the cert/key files; on change, reload them into the SAME
        SSLContext — new handshakes present the rotated certificate while
        the listener and every established connection stay up (the
        rotation contract the certificate controller relies on)."""
        import os

        def stamp():
            try:
                return (os.stat(self.certfile).st_mtime_ns,
                        os.stat(self.keyfile).st_mtime_ns
                        if self.keyfile else 0)
            except OSError:
                return None

        last = stamp()
        while not self._cert_watch_stop.wait(self.cert_reload_seconds):
            now = stamp()
            if now is None or now == last:
                continue
            try:
                self._ssl_ctx.load_cert_chain(self.certfile,
                                              self.keyfile or None)
                self.cert_reloads += 1
                last = now
            except (OSError, ValueError):
                # Mid-rotation read (cert/key momentarily mismatched):
                # keep serving the previous pair; next poll retries.
                pass

    def _make_redirect_handler(gw: "Gateway"):
        class Redirect(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _redirect(self):
                host = (self.headers.get("Host") or "").split(":")[0]
                if not host:
                    # No Host → no valid Location to build.
                    self.send_response(400)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                target = gw.redirect_target_port
                port = "" if target in (None, 443) else f":{target}"
                self.send_response(301)
                self.send_header("Location",
                                 f"https://{host}{port}{self.path}")
                self.send_header("Content-Length", "0")
                self.end_headers()

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _redirect

        return Redirect

    def start(self) -> None:
        self._proxy = ThreadingHTTPServer(
            ("0.0.0.0", self.port), self._make_proxy_handler()
        )
        self.port = self._proxy.server_address[1]  # resolve port 0
        if self.certfile:
            import ssl

            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(self.certfile,
                                          self.keyfile or None)
            self._proxy.socket = self._ssl_ctx.wrap_socket(
                self._proxy.socket, server_side=True
            )
            if self.cert_reload_seconds > 0:
                threading.Thread(target=self._watch_certs,
                                 daemon=True).start()
        threading.Thread(target=self._proxy.serve_forever,
                         daemon=True).start()
        if self.redirect_port is not None:
            self._redirect = ThreadingHTTPServer(
                ("0.0.0.0", self.redirect_port),
                self._make_redirect_handler(),
            )
            self.redirect_port = self._redirect.server_address[1]
            threading.Thread(target=self._redirect.serve_forever,
                             daemon=True).start()
        if self.admin_port:
            self._admin = ThreadingHTTPServer(
                ("0.0.0.0", self.admin_port), self._make_admin_handler()
            )
            threading.Thread(target=self._admin.serve_forever,
                             daemon=True).start()
        if self.probe_interval > 0:
            threading.Thread(target=self._probe_upstreams,
                             daemon=True).start()

    def stop(self) -> None:
        self._cert_watch_stop.set()
        for httpd in (self._proxy, self._admin, self._redirect):
            if httpd:
                httpd.shutdown()
