"""API gateway: annotation-discovered reverse proxy.

The ambassador analogue (kubeflow/common/ambassador.libsonnet:7-226): every
platform Service that wants routing carries a
`kubeflow-tpu.org/gateway-route` annotation (the `getambassador.io/config`
pattern — route spec {name, prefix, service, rewrite}); the gateway watches
Services, keeps a longest-prefix route table, and proxies requests to the
backing service. Optional forward-auth: every request is checked against the
gatekeeper's /auth endpoint first (the IAP/basic-auth ingress role,
kubeflow/common/basic-auth.libsonnet).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

import yaml

from kubeflow_tpu.k8s.client import K8sClient
from kubeflow_tpu.manifests.core import GATEWAY_ROUTE_ANNOTATION

log = logging.getLogger(__name__)

# Hop-by-hop headers never forwarded (RFC 7230 §6.1).
_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailers", "transfer-encoding", "upgrade",
    "host", "content-length",
}


@dataclass(frozen=True)
class Route:
    name: str
    prefix: str
    service: str  # host:port
    rewrite: str = "/"

    def target_for(self, path: str) -> str:
        """Rewrite `path` (which startswith prefix) onto the backend."""
        rest = path[len(self.prefix):]
        base = self.rewrite if self.rewrite.endswith("/") else self.rewrite + "/"
        return "http://" + self.service + base + rest.lstrip("/")


def routes_from_service(svc: dict) -> list[Route]:
    raw = svc.get("metadata", {}).get("annotations", {}).get(
        GATEWAY_ROUTE_ANNOTATION
    )
    if not raw:
        return []
    try:
        specs = yaml.safe_load(raw)
    except yaml.YAMLError:
        log.warning("bad route annotation on %s",
                    svc["metadata"].get("name"))
        return []
    if isinstance(specs, dict):
        specs = [specs]
    routes = []
    for spec in specs or []:
        try:
            routes.append(Route(
                name=spec["name"], prefix=spec["prefix"],
                service=spec["service"], rewrite=spec.get("rewrite", "/"),
            ))
        except (KeyError, TypeError):
            log.warning("incomplete route spec in %s",
                        svc["metadata"].get("name"))
    return routes


class RouteTable:
    """Longest-prefix route lookup, refreshed from Service annotations."""

    def __init__(self) -> None:
        self._routes: list[Route] = []
        self._lock = threading.Lock()

    def set_routes(self, routes: list[Route]) -> None:
        with self._lock:
            self._routes = sorted(routes, key=lambda r: -len(r.prefix))

    def refresh(self, client: K8sClient, namespace: str | None = None) -> int:
        routes = []
        for svc in client.list("v1", "Service", namespace):
            routes.extend(routes_from_service(svc))
        self.set_routes(routes)
        return len(routes)

    def match(self, path: str) -> Route | None:
        with self._lock:
            for r in self._routes:
                if path.startswith(r.prefix):
                    return r
        return None

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [vars(r) for r in self._routes]


class Gateway:
    """The proxy + admin servers.

    ``resolve`` maps a route's `host:port` service address to the address to
    actually dial — identity in-cluster, overridden in tests to point at
    local fixture backends.
    """

    def __init__(
        self,
        table: RouteTable,
        *,
        port: int = 8080,
        admin_port: int = 8877,
        auth_url: str = "",
        resolve: Callable[[str], str] | None = None,
        certfile: str = "",
        keyfile: str = "",
    ):
        self.table = table
        self.port = port
        self.admin_port = admin_port
        self.auth_url = auth_url
        self.resolve = resolve or (lambda addr: addr)
        # TLS termination at the gateway (the iap-ingress/cert-manager
        # role, kubeflow/gcp/iap.libsonnet): cert+key mounted from a
        # Secret; empty = plain HTTP (in-mesh or behind an LB).
        self.certfile = certfile
        self.keyfile = keyfile
        self.requests_total = 0
        self.errors_total = 0
        self._proxy: ThreadingHTTPServer | None = None
        self._admin: ThreadingHTTPServer | None = None

    # -- auth ---------------------------------------------------------------

    def _authorized(self, handler: BaseHTTPRequestHandler) -> bool:
        if not self.auth_url:
            return True
        req = urllib.request.Request(self.auth_url, method="GET")
        cookie = handler.headers.get("Cookie")
        if cookie:
            req.add_header("Cookie", cookie)
        auth = handler.headers.get("Authorization")
        if auth:
            req.add_header("Authorization", auth)
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                return 200 <= resp.status < 300
        except urllib.error.HTTPError:
            return False
        except OSError:
            return False

    # -- proxy --------------------------------------------------------------

    def _make_proxy_handler(gw: "Gateway"):
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _respond(self, code: int, body: bytes,
                         headers: dict | None = None) -> None:
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                if headers is None or "Content-Type" not in headers:
                    self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _handle(self):
                gw.requests_total += 1
                if self.path == "/healthz":
                    self._respond(200, b'{"status":"ok"}')
                    return
                route = gw.table.match(self.path)
                if route is None:
                    gw.errors_total += 1
                    self._respond(
                        404,
                        json.dumps({"error": f"no route for {self.path}"})
                        .encode(),
                    )
                    return
                if not gw._authorized(self):
                    self._respond(
                        401, json.dumps({"error": "unauthorized",
                                         "login": "/login"}).encode(),
                    )
                    return
                target = route.target_for(self.path)
                # Re-point at the resolved backend address.
                target = target.replace(route.service,
                                        gw.resolve(route.service), 1)
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else None
                req = urllib.request.Request(
                    target, data=body, method=self.command,
                )
                for k, v in self.headers.items():
                    if k.lower() not in _HOP_HEADERS:
                        req.add_header(k, v)
                req.add_header("X-Forwarded-Prefix", route.prefix)
                try:
                    with urllib.request.urlopen(req, timeout=60) as resp:
                        payload = resp.read()
                        headers = {
                            k: v for k, v in resp.headers.items()
                            if k.lower() not in _HOP_HEADERS
                        }
                        self._respond(resp.status, payload, headers)
                except urllib.error.HTTPError as e:
                    self._respond(e.code, e.read(),
                                  {"Content-Type": e.headers.get(
                                      "Content-Type", "application/json")})
                except OSError as e:
                    gw.errors_total += 1
                    self._respond(
                        502,
                        json.dumps({"error": f"upstream {route.service}: {e}"})
                        .encode(),
                    )

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _handle

        return Handler

    def _make_admin_handler(gw: "Gateway"):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/routes":
                    body = json.dumps(gw.table.snapshot()).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    body = (
                        "# TYPE gateway_requests_total counter\n"
                        f"gateway_requests_total {gw.requests_total}\n"
                        "# TYPE gateway_errors_total counter\n"
                        f"gateway_errors_total {gw.errors_total}\n"
                    ).encode()
                    ctype = "text/plain"
                elif self.path in ("/healthz", "/readyz"):
                    body, ctype = b'{"status":"ok"}', "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        return Handler

    def start(self) -> None:
        self._proxy = ThreadingHTTPServer(
            ("0.0.0.0", self.port), self._make_proxy_handler()
        )
        if self.certfile:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.certfile, self.keyfile or None)
            self._proxy.socket = ctx.wrap_socket(
                self._proxy.socket, server_side=True
            )
        threading.Thread(target=self._proxy.serve_forever,
                         daemon=True).start()
        if self.admin_port:
            self._admin = ThreadingHTTPServer(
                ("0.0.0.0", self.admin_port), self._make_admin_handler()
            )
            threading.Thread(target=self._admin.serve_forever,
                             daemon=True).start()

    def stop(self) -> None:
        for httpd in (self._proxy, self._admin):
            if httpd:
                httpd.shutdown()
