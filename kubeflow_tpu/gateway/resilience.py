"""Upstream resilience: health/circuit breaking, bandit routing, outlier
scoring.

The envoy outlier-detection + seldon router roles the ambassador config
delegates to sidecars in the reference — implemented natively in the
platform's front door (see each class docstring for the reference
citations).
"""

from __future__ import annotations

import json
import threading
import time
import socket
from typing import TYPE_CHECKING, Callable

if TYPE_CHECKING:  # annotation-only: routing imports nothing from here
    from kubeflow_tpu.gateway.routing import Route


class OutlierStats:
    """Route-attached anomaly scoring — the seldon outlier-detector
    variant (/root/reference/kubeflow/seldon/prototypes/
    outlier-detector-v1alpha2.jsonnet:1-128 attaches a Mahalanobis
    scorer to a model route). Platform recast: a running z-score over a
    scalar feature of each prediction request (mean |value| of the
    instances payload), maintained per route over a sliding window.
    Requests scoring beyond the route's threshold are tagged
    (X-Outlier/X-Outlier-Score response headers — the streamed relay
    never buffers bodies, so tagging rides headers) and counted into the
    outlier-rate metric."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # route -> (window deque, outliers, scored)
        self._windows: dict[str, object] = {}
        self._counts: dict[str, list[int]] = {}

    @staticmethod
    def feature(body: bytes | None) -> float | None:
        """Scalar feature of a prediction request: mean |x| over every
        numeric leaf of "instances". None = not scoreable (no/bad JSON,
        no numerics) — never an error, scoring must not break proxying."""
        if not body:
            return None
        try:
            payload = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None
        total, n = 0.0, 0
        stack = [payload.get("instances")
                 if isinstance(payload, dict) else payload]
        while stack:
            node = stack.pop()
            if isinstance(node, bool):
                continue
            if isinstance(node, (int, float)):
                total += abs(float(node))
                n += 1
            elif isinstance(node, list):
                stack.extend(node)
            elif isinstance(node, dict):
                stack.extend(node.values())
        return total / n if n else None

    # Baseline points required before anything is flagged: a 2-sample
    # window's std is noise, and normal jitter would score "infinite".
    WARMUP = 10

    def score(self, route: str, value: float, *, window: int,
              threshold: float) -> tuple[float, bool]:
        """Running z-score of ``value`` against the route's window
        (scored BEFORE insertion, so one huge request can't mask
        itself); returns (score, is_outlier). Warmup requests build the
        baseline and are never flagged."""
        import collections
        import math

        with self._lock:
            win = self._windows.setdefault(
                route, collections.deque(maxlen=max(window, 2))
            )
            counts = self._counts.setdefault(route, [0, 0])
            if win.maxlen != max(window, 2):
                # Window reconfigured (annotation re-applied): carry the
                # most recent baseline into the new size.
                win = collections.deque(win, maxlen=max(window, 2))
                self._windows[route] = win
            warm = len(win) >= min(self.WARMUP, win.maxlen)
            if len(win) >= 2:
                mean = sum(win) / len(win)
                var = sum((v - mean) ** 2 for v in win) / len(win)
                std = math.sqrt(var)
                z = abs(value - mean) / std if std > 1e-12 else (
                    0.0 if abs(value - mean) < 1e-12 else float("inf")
                )
            else:
                z = 0.0
            outlier = warm and z > threshold
            counts[1] += 1
            if outlier:
                counts[0] += 1
            else:
                # Outliers are excluded from the baseline, or a burst of
                # them would normalize itself into "normal".
                win.append(value)
            return (round(z, 4) if z != float("inf") else z, outlier)

    def snapshot(self, route: str) -> dict:
        with self._lock:
            outliers, scored = self._counts.get(route, (0, 0))
            return {"outliers": outliers, "scored": scored,
                    "rate": round(outliers / scored, 4) if scored else 0.0}

    def totals(self) -> tuple[int, int]:
        with self._lock:
            return (sum(c[0] for c in self._counts.values()),
                    sum(c[1] for c in self._counts.values()))


class UpstreamHealth:
    """Per-backend health with circuit breaking (the envoy outlier-
    detection role ambassador delegates to envoy; this platform's front
    door implements it natively):

    - passive observation: every proxied request records success/failure
      (connect errors and 5xx); ``failure_threshold`` consecutive
      failures EJECT the backend from every route's pick set for
      ``ejection_seconds``;
    - half-open recovery: after the ejection window one trial request is
      let through — success closes the circuit, failure re-ejects with
      doubled backoff (capped 10×);
    - active probes: a prober thread TCP-connects each known backend
      every ``probe_interval`` seconds so an upstream that died between
      requests is ejected (and a recovered one readmitted) without
      client traffic paying for the discovery.
    """

    def __init__(self, *, failure_threshold: int = 3,
                 ejection_seconds: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = failure_threshold
        self.ejection_seconds = ejection_seconds
        self.clock = clock
        self._lock = threading.Lock()
        # service -> {fails, ejected_until, ejections, state-extras}
        self._state: dict[str, dict] = {}

    def _cell(self, service: str) -> dict:
        return self._state.setdefault(service, {
            "consecutive_failures": 0, "ejected_until": 0.0,
            "ejections": 0, "half_open_inflight": False,
            "trial_started": 0.0, "last_change": self.clock(),
            "warming": False,
        })

    def record_success(self, service: str) -> None:
        with self._lock:
            cell = self._cell(service)
            recovered = (cell["consecutive_failures"]
                         >= self.failure_threshold)
            cell.update(consecutive_failures=0, ejected_until=0.0,
                        half_open_inflight=False)
            if recovered:
                cell.update(ejections=0, last_change=self.clock())

    # A half-open trial that never reported back (e.g. the request rode
    # an upgrade tunnel, which doesn't record outcomes) expires so the
    # backend isn't stuck "trial in flight" forever.
    TRIAL_TIMEOUT = 30.0

    def record_failure(self, service: str) -> None:
        with self._lock:
            cell = self._cell(service)
            cell["consecutive_failures"] += 1
            cell["half_open_inflight"] = False
            if cell["consecutive_failures"] >= self.failure_threshold:
                # Re-eject with doubled backoff per consecutive ejection
                # (half-open trial failed), capped at 10x — exponent
                # clamped so a long-dead backend can't grow a bigint.
                backoff = self.ejection_seconds * min(
                    2 ** min(cell["ejections"], 4), 10
                )
                cell["ejected_until"] = self.clock() + backoff
                cell["ejections"] += 1
                cell["last_change"] = self.clock()

    def _eligible_locked(self, cell: dict | None) -> bool:
        if cell is None or cell["consecutive_failures"] \
                < self.failure_threshold:
            return True
        if self.clock() < cell["ejected_until"]:
            return False
        if cell["half_open_inflight"] and (
                self.clock() - cell["trial_started"] < self.TRIAL_TIMEOUT):
            return False
        return True  # window elapsed: a trial may begin

    def set_warming(self, service: str, warming: bool) -> None:
        """A newborn upstream answering ``/healthz`` with ``warming`` is
        alive-but-not-serving: route-excluded like an ejection but with
        NO failure-counter penalty — it exits the moment its dispatch
        set finishes compiling, with zero half-open walk to pay."""
        with self._lock:
            cell = self._cell(service)
            if cell.get("warming") != bool(warming):
                cell["warming"] = bool(warming)
                cell["last_change"] = self.clock()

    def admits(self, service: str) -> bool:
        """Side-effect-free eligibility: healthy (and not a warming
        newborn), or ejection window elapsed with no trial in flight."""
        with self._lock:
            cell = self._state.get(service)
            if cell is not None and cell.get("warming"):
                return False
            return self._eligible_locked(cell)

    def begin_trial(self, service: str) -> None:
        """Mark the half-open trial as in flight for the backend a
        request was ACTUALLY routed to (never during pick-set filtering —
        an unpicked backend must not have its one trial consumed)."""
        with self._lock:
            cell = self._state.get(service)
            if (cell is not None
                    and cell["consecutive_failures"]
                    >= self.failure_threshold
                    and self.clock() >= cell["ejected_until"]):
                cell["half_open_inflight"] = True
                cell["trial_started"] = self.clock()

    def filter_healthy(self, services: list[str]) -> list[str]:
        """The pick set: ejected and warming backends drop out; if
        EVERYTHING is excluded, fail open with the full set (a wrong
        502 beats blackholing when the health data itself is suspect —
        and an all-warming pool serving slowly beats serving nobody)."""
        healthy = [s for s in services if self.admits(s)]
        return healthy or list(services)

    def probe(self, services: list[str],
              resolve: Callable[[str], str]) -> None:
        """Active probe of every service: a TCP connect is the
        liveness signal (protocol-agnostic — 'something is
        listening'), then a best-effort ``GET /healthz`` on the same
        socket distinguishes a WARMING newborn (mid weight-install /
        dispatch-set compile) from a serving one. Anything that
        connects but doesn't speak the health protocol reads as
        serving — no worse than the TCP-only probe."""
        for service in services:
            addr = resolve(service)
            host, _, port_s = addr.partition(":")
            try:
                with socket.create_connection(
                        (host, int(port_s or 80)), timeout=2.0) as sock:
                    warming = self._probe_warming(sock, host)
                self.set_warming(service, warming)
                self.record_success(service)
            except OSError:
                self.record_failure(service)

    @staticmethod
    def _probe_warming(sock: socket.socket, host: str) -> bool:
        """Raw-socket health read on the already-connected probe
        socket. Returns True only on an explicit ``"warming"`` status;
        a non-HTTP listener, timeout, or parse failure is False —
        warming must only ever be asserted by the upstream itself."""
        try:
            sock.settimeout(2.0)
            sock.sendall((f"GET /healthz HTTP/1.1\r\nHost: {host}\r\n"
                          "Connection: close\r\n\r\n").encode())
            data = b""
            while len(data) < 65536:
                chunk = sock.recv(4096)
                if not chunk:
                    break
                data += chunk
                if b"}" in data:  # the one-line JSON body landed
                    break
            return b'"warming"' in data
        except OSError:
            return False

    def snapshot(self) -> dict:
        with self._lock:
            now = self.clock()
            return {
                svc: {
                    "healthy": cell["consecutive_failures"]
                    < self.failure_threshold,
                    "consecutive_failures": cell["consecutive_failures"],
                    "ejected_for_seconds": round(
                        max(0.0, cell["ejected_until"] - now), 2),
                    "ejections": cell["ejections"],
                    "warming": bool(cell.get("warming")),
                }
                for svc, cell in self._state.items()
            }


class BackendLoad:
    """Per-backend in-flight request counter — the gateway-local queue
    depth the ``prefix-affine`` route strategy spills on. Passive and
    exact for the traffic THIS gateway carries (the pressure signal must
    not depend on a metrics scrape being fresh): acquired when a request
    is dispatched upstream, released when its relay finishes, streamed
    bodies included."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._in_flight: dict[str, int] = {}

    def acquire(self, service: str) -> None:
        with self._lock:
            self._in_flight[service] = self._in_flight.get(service, 0) + 1

    def release(self, service: str) -> None:
        with self._lock:
            n = self._in_flight.get(service, 0) - 1
            if n > 0:
                self._in_flight[service] = n
            else:
                self._in_flight.pop(service, None)

    def depth(self, service: str) -> int:
        with self._lock:
            return self._in_flight.get(service, 0)

    def least_loaded(self, services: list[str]) -> str | None:
        """The lowest-depth service; ties keep the CALLER's order (the
        rendezvous spill sequence), so spill targets are deterministic."""
        with self._lock:
            if not services:
                return None
            return min(services,
                       key=lambda s: (self._in_flight.get(s, 0),
                                      services.index(s)))

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._in_flight)


class KvFillCache:
    """Staleness-bounded KV-pool-fill signal per backend, scraped from
    the model server's exposition (``serving_kv_bytes_in_use`` /
    ``serving_kv_bytes_total``) — the gateway-side complement to the
    local in-flight depth the prefix-affine spill reads (the in-process
    ``DecoderFleet`` already honors ``kv_pressure``; this brings the
    HTTP path to parity).

    The request path only ever READS the cache: a fresh value serves
    directly; a stale one serves while kicking off at most one
    background refresh (the scrape's network latency never lands on a
    client request); a backend never scraped — or whose last scrape
    failed — yields None, which the spill policy treats as "signal
    unavailable", NEVER as "pool empty" (an unscrapeable replica must
    not look like the least-loaded spill target)."""

    def __init__(self, *, ttl: float = 5.0, fetch=None,
                 clock: Callable[[], float] = time.monotonic):
        self.ttl = float(ttl)
        self.clock = clock
        self.fetch = fetch or self._http_fetch
        self._lock = threading.Lock()
        # service -> {"fill": float | None, "at": t, "refreshing": bool}
        self._cells: dict[str, dict] = {}
        self.scrapes = 0
        self.scrape_failures = 0

    @staticmethod
    def _http_fetch(addr: str, timeout: float = 2.0) -> float | None:
        """One exposition GET reduced to in_use/total (None on any
        failure or an unpriced pool — no bytes gauge means no signal)."""
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"http://{addr}/monitoring/prometheus/metrics",
                    timeout=timeout) as resp:
                text = resp.read().decode("utf-8", "replace")
        except (OSError, ValueError):
            return None
        vals = {}
        for line in text.splitlines():
            parts = line.split()
            if len(parts) == 2 and parts[0] in ("serving_kv_bytes_in_use",
                                                "serving_kv_bytes_total"):
                try:
                    vals[parts[0]] = float(parts[1])
                except ValueError:
                    continue
        total = vals.get("serving_kv_bytes_total", 0.0)
        if total <= 0:
            return None
        return vals.get("serving_kv_bytes_in_use", 0.0) / total

    def _refresh(self, service: str, addr: str) -> None:
        fill = self.fetch(addr)
        with self._lock:
            cell = self._cells.setdefault(service, {})
            if fill is None:
                # Keep serving the stale value inside a grace window
                # (2x ttl); past it the signal goes dark rather than
                # spill on ancient data.
                self.scrape_failures += 1
                at = cell.get("at", 0.0)
                if self.clock() - at > 2 * self.ttl:
                    cell["fill"] = None
            else:
                cell.update(fill=fill, at=self.clock())
            cell["refreshing"] = False
            self.scrapes += 1

    def fill(self, service: str,
             resolve: Callable[[str], str] = lambda a: a) -> float | None:
        """Last-known fill fraction for ``service`` (None = no signal).
        Triggers ONE background refresh when the value is stale."""
        with self._lock:
            cell = self._cells.setdefault(
                service, {"fill": None, "at": 0.0, "refreshing": False})
            fresh = self.clock() - cell.get("at", 0.0) < self.ttl \
                and cell.get("fill") is not None
            if not fresh and not cell["refreshing"]:
                cell["refreshing"] = True
                # tpu-lint: disable=thread-no-join -- one-shot refresh; exits after a single scrape
                threading.Thread(
                    target=self._refresh,
                    args=(service, resolve(service)),
                    daemon=True).start()
            return cell.get("fill")

    def snapshot(self) -> dict:
        with self._lock:
            return {svc: cell.get("fill")
                    for svc, cell in self._cells.items()}


class BanditStats:
    """Per-(route, backend) reward averages for epsilon-greedy routes."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: dict[tuple[str, str], list[float]] = {}

    def record(self, route: str, service: str, reward: float) -> None:
        with self._lock:
            cell = self._stats.setdefault((route, service), [0.0, 0])
            cell[0] += reward
            cell[1] += 1

    def pick(self, route: Route, rng, services: list[str] | None = None
             ) -> str:
        """Explore uniformly with prob epsilon; otherwise exploit the best
        mean reward. Untried backends are optimistic (mean 1.0), so every
        variant gets traffic before exploitation locks in. ``services``
        restricts the arms (the health layer's ejection filter)."""
        if services is None:
            services = [b[0] for b in route.backends]
        if rng.random() < route.epsilon:
            return rng.choice(services)
        with self._lock:
            def mean(svc: str) -> float:
                total, n = self._stats.get((route.name, svc), (0.0, 0))
                return total / n if n else 1.0

            best = max(mean(s) for s in services)
            top = [s for s in services if mean(s) == best]
        return rng.choice(top)

    def snapshot(self, route_name: str) -> dict:
        with self._lock:
            return {
                svc: {"reward_sum": round(total, 4), "trials": n,
                      "mean": round(total / n, 4) if n else None}
                for (rname, svc), (total, n) in self._stats.items()
                if rname == route_name
            }


