"""Gateway entrypoint: `python -m kubeflow_tpu.gateway --port=8080
--admin-port=8877 --namespace=kubeflow` (the ambassador Deployment command,
kubeflow/common/ambassador.libsonnet)."""

from __future__ import annotations

import argparse
import logging
import sys
import time

from kubeflow_tpu.gateway import Gateway, RouteTable
from kubeflow_tpu.runtime import add_client_args, client_from_args, strip_glog_args

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="kubeflow-tpu API gateway")
    add_client_args(p)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--admin-port", type=int, default=8877)
    p.add_argument("--auth-url", default="",
                   help="forward-auth check endpoint (gatekeeper /auth); "
                        "empty = no auth")
    p.add_argument("--refresh-seconds", type=float, default=15.0)
    p.add_argument("--tls-cert", default="",
                   help="PEM cert chain for TLS termination (the "
                        "iap/cert-manager ingress role); empty = HTTP")
    p.add_argument("--tls-key", default="", help="PEM private key")
    p.add_argument("--watch-certs", type=float, default=5.0,
                   help="seconds between cert-file freshness checks; the "
                        "certificate controller's rotations hot-reload "
                        "without dropping connections (0 disables)")
    p.add_argument("--redirect-port", type=int, default=None,
                   help="plain-HTTP port 301ing to the HTTPS entrypoint "
                        "(components/https-redirect analogue)")
    p.add_argument("--redirect-target-port", type=int, default=None,
                   help="externally advertised HTTPS port for redirect "
                        "Locations (default: omitted = 443); required "
                        "when the public port differs from the bind port")
    p.add_argument("--serve-acme-challenges", action="store_true",
                   help="serve /.well-known/acme-challenge/<token> from "
                        "the certificate controller's published tokens")
    p.add_argument("--jwt-issuer", default="",
                   help="require bearer id-tokens with this iss claim "
                        "(the envoy jwt-auth filter role); empty = no "
                        "token requirement")
    p.add_argument("--jwt-audience", default="kubeflow-tpu",
                   help="required aud claim on bearer tokens")
    p.add_argument("--jwks-uri", default="",
                   help="where to fetch verification keys (the "
                        "gatekeeper's /.well-known/jwks.json)")
    p.add_argument("--jwt-bypass", default="",
                   help="JSON bypass list, e.g. "
                        '[{"http_method":"GET","path_exact":"/healthz"}]')
    p.add_argument("--jwt-skew", type=float, default=60.0,
                   help="clock-skew allowance in seconds")
    p.add_argument("--max-body-bytes", type=int, default=0,
                   help="reject request bodies larger than this with "
                        "413 before reading them (0 = unbounded)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    client = client_from_args(args)
    table = RouteTable()
    challenge_lookup = None
    if args.serve_acme_challenges:
        from kubeflow_tpu.operators.certificates import (
            ACME_CHALLENGE_CONFIGMAP,
        )

        def challenge_lookup(token: str) -> str | None:
            from kubeflow_tpu.k8s.client import ApiError

            try:
                cm = client.get("v1", "ConfigMap",
                                ACME_CHALLENGE_CONFIGMAP, args.namespace)
            except ApiError as e:
                if e.code != 404:
                    # RBAC/addressing problems must be debuggable, not
                    # silent 404s on every challenge.
                    log.warning("acme challenge lookup failed: %s", e)
                return None
            # HTTP-01 body is the token itself (key-authorization
            # simplified to the platform's in-cluster validation).
            return token if token in (cm.get("data") or {}).values() \
                else None

    jwt_verifier = None
    if args.jwt_issuer:
        if not args.jwks_uri:
            p.error("--jwt-issuer requires --jwks-uri")
        from kubeflow_tpu.gateway.jwt_auth import (
            JwtVerifier,
            bypass_from_specs,
        )

        jwt_verifier = JwtVerifier(
            args.jwks_uri, issuer=args.jwt_issuer,
            audience=args.jwt_audience,
            bypass=bypass_from_specs(args.jwt_bypass),
            skew_seconds=args.jwt_skew,
        )
    gw = Gateway(table, port=args.port, admin_port=args.admin_port,
                 auth_url=args.auth_url, certfile=args.tls_cert,
                 keyfile=args.tls_key,
                 cert_reload_seconds=args.watch_certs,
                 redirect_port=args.redirect_port,
                 redirect_target_port=args.redirect_target_port,
                 challenge_lookup=challenge_lookup,
                 jwt_verifier=jwt_verifier,
                 max_body_bytes=args.max_body_bytes)
    gw.start()
    log.info("gateway on :%d (admin :%d)", args.port, args.admin_port)
    try:
        while True:
            try:
                n = table.refresh(client, args.namespace)
                log.debug("route table refreshed: %d routes", n)
            except Exception as e:  # keep serving on apiserver blips
                log.warning("route refresh failed: %s", e)
            time.sleep(args.refresh_seconds)
    except KeyboardInterrupt:
        gw.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
