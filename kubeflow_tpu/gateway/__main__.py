"""Gateway entrypoint: `python -m kubeflow_tpu.gateway --port=8080
--admin-port=8877 --namespace=kubeflow` (the ambassador Deployment command,
kubeflow/common/ambassador.libsonnet)."""

from __future__ import annotations

import argparse
import logging
import sys
import time

from kubeflow_tpu.gateway import Gateway, RouteTable
from kubeflow_tpu.runtime import add_client_args, client_from_args, strip_glog_args

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="kubeflow-tpu API gateway")
    add_client_args(p)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--admin-port", type=int, default=8877)
    p.add_argument("--auth-url", default="",
                   help="forward-auth check endpoint (gatekeeper /auth); "
                        "empty = no auth")
    p.add_argument("--refresh-seconds", type=float, default=15.0)
    p.add_argument("--tls-cert", default="",
                   help="PEM cert chain for TLS termination (the "
                        "iap/cert-manager ingress role); empty = HTTP")
    p.add_argument("--tls-key", default="", help="PEM private key")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    client = client_from_args(args)
    table = RouteTable()
    gw = Gateway(table, port=args.port, admin_port=args.admin_port,
                 auth_url=args.auth_url, certfile=args.tls_cert,
                 keyfile=args.tls_key)
    gw.start()
    log.info("gateway on :%d (admin :%d)", args.port, args.admin_port)
    try:
        while True:
            try:
                n = table.refresh(client, args.namespace)
                log.debug("route table refreshed: %d routes", n)
            except Exception as e:  # keep serving on apiserver blips
                log.warning("route refresh failed: %s", e)
            time.sleep(args.refresh_seconds)
    except KeyboardInterrupt:
        gw.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
