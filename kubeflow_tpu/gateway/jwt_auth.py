"""Per-route JWT verification at the gateway — the envoy `jwt-auth`
filter role (/root/reference/kubeflow/gcp/iap.libsonnet:589-600: issuer,
audiences, jwks_uri, jwt_headers, bypass_jwt path list).

:class:`JwksCache` pulls the issuer's key set and re-fetches on an
unknown ``kid`` (rate-limited), which is what makes key rotation
zero-downtime: the first token signed by a fresh key triggers the
refresh that admits it. :class:`JwtVerifier` is the request-time policy:
bearer tokens from ``Authorization`` or the platform assertion header,
verified for signature/issuer/audience/expiry with clock skew, with a
method+path bypass list mirroring ``bypass_jwt``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable, Mapping

from kubeflow_tpu.auth.tokens import TokenError, decode_unverified, verify

# The x-goog-iap-jwt-assertion analogue (iap.libsonnet:597): callers
# that need Authorization for the upstream put the platform token here.
ASSERTION_HEADER = "x-kubeflow-jwt-assertion"


@dataclass(frozen=True)
class BypassRule:
    """One `bypass_jwt` entry: method + exact path or prefix."""

    http_method: str = "GET"
    path_exact: str = ""
    path_prefix: str = ""

    def matches(self, method: str, path: str) -> bool:
        if self.http_method and method.upper() != self.http_method.upper():
            return False
        if self.path_exact:
            return path == self.path_exact
        return bool(self.path_prefix) and path.startswith(self.path_prefix)


class JwksCache:
    """Cached JWKS with unknown-kid refresh.

    ``source`` is either a URL (the gatekeeper's /.well-known/jwks.json)
    or a zero-arg callable returning the key-set dict (in-process tests,
    custom transports). A kid the cached set doesn't know gets an
    immediate re-fetch — a token signed by a freshly-rotated key must
    never see a 401 window — but each still-unknown kid is then remembered
    for ``min_refresh_seconds``, and miss-triggered fetches draw from a
    small per-window budget, so neither a replayed garbage token nor a
    flood of random kids can hammer the issuer (the envoy jwks
    cache-duration behavior).
    """

    MISS_FETCH_BUDGET = 5  # miss-triggered fetches per refresh window

    def __init__(self, source: str | Callable[[], Mapping], *,
                 refresh_seconds: float = 300.0,
                 min_refresh_seconds: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self._fetch = (source if callable(source)
                       else lambda: self._fetch_url(source))
        self.refresh_seconds = refresh_seconds
        self.min_refresh_seconds = min_refresh_seconds
        self.clock = clock
        self._lock = threading.Lock()
        self._jwks: dict = {"keys": []}
        self._fetched_at = float("-inf")
        self._attempted_at = float("-inf")  # last attempt, incl. failures
        self._inflight = False
        self._miss_at: dict[str, float] = {}  # kid -> last miss-fetch time
        self._miss_window_start = float("-inf")
        self._miss_budget = self.MISS_FETCH_BUDGET
        self.fetches = 0
        self.fetch_errors = 0

    @staticmethod
    def _fetch_url(url: str) -> dict:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.loads(resp.read())

    def _has_kid(self, kid: str) -> bool:
        return any(k.get("kid") == kid for k in self._jwks["keys"])

    def jwks(self, *, want_kid: str | None = None) -> dict:
        """Current key set; stale or kid-missing sets are re-fetched.

        The HTTP fetch happens OUTSIDE the lock and at most one request
        performs it at a time — a slow or dead issuer costs one in-flight
        prober, never the whole data path. Failed attempts advance the
        attempt clock, so a down issuer is retried at most once per
        ``min_refresh_seconds`` on the staleness path.
        """
        with self._lock:
            now = self.clock()
            stale = (now - self._fetched_at > self.refresh_seconds
                     and now - self._attempted_at
                     > self.min_refresh_seconds)
            missing = want_kid is not None and not self._has_kid(want_kid)
            if missing:
                # Per-kid miss memory: the first sighting of a kid
                # re-fetches (zero-downtime rotation); a repeat of a kid
                # the issuer doesn't know waits out the window, and the
                # per-window budget caps what a flood of RANDOM kids can
                # trigger (a real rotation needs exactly one).
                last = self._miss_at.get(want_kid, float("-inf"))
                if now - last <= self.min_refresh_seconds:
                    missing = False
                else:
                    if (now - self._miss_window_start
                            > self.min_refresh_seconds):
                        self._miss_window_start = now
                        self._miss_budget = self.MISS_FETCH_BUDGET
                    if self._miss_budget <= 0:
                        missing = False
                    else:
                        self._miss_budget -= 1
            if (not stale and not missing) or self._inflight:
                return self._jwks
            self._inflight = True
            self._attempted_at = now
            self.fetches += 1
        ok, jwks = False, {}
        try:
            jwks = dict(self._fetch())
            ok = isinstance(jwks.get("keys"), list)
        except (OSError, ValueError):
            # Keep serving the cached set — verification degrades only
            # for tokens signed by keys we have never seen.
            pass
        with self._lock:
            self._inflight = False
            if ok:
                self._jwks = jwks
                self._fetched_at = self.clock()
            else:
                self.fetch_errors += 1
            if want_kid is not None and not self._has_kid(want_kid):
                if len(self._miss_at) > 1024:  # bound the memory
                    self._miss_at = {
                        k: t for k, t in self._miss_at.items()
                        if now - t <= self.min_refresh_seconds
                    }
                self._miss_at[want_kid] = now
            return self._jwks


class JwtVerifier:
    """The gateway's per-request token check."""

    def __init__(self, jwks: JwksCache | str | Callable[[], Mapping], *,
                 issuer: str, audience: str,
                 bypass: tuple[BypassRule, ...] = (),
                 skew_seconds: float = 60.0,
                 now: Callable[[], float] | None = None):
        self.cache = jwks if isinstance(jwks, JwksCache) else JwksCache(jwks)
        self.issuer = issuer
        self.audience = audience
        self.bypass = tuple(bypass)
        self.skew_seconds = skew_seconds
        self.now = now
        self.verified_total = 0
        self.rejected_total = 0

    def bypassed(self, method: str, path: str) -> bool:
        path = path.partition("?")[0]  # match on the path, not the query
        return any(r.matches(method, path) for r in self.bypass)

    @staticmethod
    def token_from_headers(headers: Mapping) -> str | None:
        assertion = headers.get(ASSERTION_HEADER)
        if assertion:
            return assertion.strip()
        authz = headers.get("Authorization") or ""
        if authz.startswith("Bearer "):
            return authz[7:].strip()
        return None

    def check(self, method: str, path: str,
              headers: Mapping) -> tuple[dict | None, str]:
        """(claims, "") when the request may pass; (None, reason) when it
        must be rejected. Bypass paths pass with no claims."""
        if self.bypassed(method, path):
            return {}, ""
        token = self.token_from_headers(headers)
        if not token:
            self.rejected_total += 1
            return None, "missing-token"
        # Route on the (unverified) kid so a fresh key triggers exactly
        # one JWKS re-fetch; verification then runs on the cached set.
        try:
            kid = decode_unverified(token)[0].get("kid")
        except TokenError:
            kid = None
        try:
            claims = verify(
                token, self.cache.jwks(want_kid=kid),
                issuer=self.issuer, audience=self.audience,
                now=self.now() if self.now else None,
                skew_seconds=self.skew_seconds,
            )
        except TokenError as e:
            self.rejected_total += 1
            return None, str(e)
        self.verified_total += 1
        return claims, ""


def bypass_from_specs(specs) -> tuple[BypassRule, ...]:
    """Parse `[{http_method, path_exact | path_prefix}, ...]` (the
    iap.libsonnet:600 bypass_jwt shape; JSON string accepted)."""
    if isinstance(specs, str):
        specs = json.loads(specs) if specs.strip() else []
    rules = []
    for spec in specs or []:
        rules.append(BypassRule(
            http_method=str(spec.get("http_method", "GET")),
            path_exact=str(spec.get("path_exact", "")),
            path_prefix=str(spec.get("path_prefix", "")),
        ))
    return tuple(rules)
