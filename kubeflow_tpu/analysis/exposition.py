"""Metrics-exposition checker: the scrape contract as rules.

Absorbs the hand-rolled grep half of ``ci/metrics_lint.sh`` and adds
the conventions the scrape consumers (autoscaler, dashboards) rely on:

- ``metrics-type-literal``: the single-renderer invariant. No string
  literal containing ``# TYPE`` may exist outside
  ``observability/metrics.py`` — every exposition surface must render
  through the one shared renderer (the bug class: a fifth hand-rolled
  renderer that types every gauge as a counter).

- ``metrics-name-convention``: every family registered via
  ``registry.counter/gauge/histogram("name", ...)`` follows
  ``{subsystem}_{name}[_{unit}]``: lowercase snake_case, at least two
  segments, a known subsystem prefix, counters ending ``_total``,
  and seconds/bytes units spelled out (no ``_ms``/``_secs``).

- ``metrics-label-vocab``: label names come from the bounded shared
  vocabulary — ad-hoc labels are how cardinality explosions and
  join-impossible dashboards start.
"""

from __future__ import annotations

import ast
import re

from kubeflow_tpu.analysis.core import Checker, FileContext, register

# Modules allowed to spell the exposition text format: the one
# renderer, the promtool-style scrape validator, and this checker.
EXEMPT_PATHS = ("observability/metrics.py", "observability/lint.py",
                "analysis/exposition.py")

SUBSYSTEMS = ("serving", "gateway", "operator", "scheduler", "train",
              "probe", "kubeflow", "analysis",
              # InferenceService autoscaler decisions (operators/
              # inference.py) — the service-facing counter family the
              # flash-crowd dashboards join on.
              "inference",
              # Self-tuning engine (operators/experiment.py): experiment
              # trial accounting and suggestion-policy counters.
              "experiment", "tuning")

LABEL_VOCAB = frozenset({
    "kind", "route", "queue", "pool", "reason", "role", "model",
    "code", "status", "service", "replica", "rule", "stage",
    # Multi-tenant QoS: label VALUES are hash-bucketed tenant ids
    # (serving/qos.py:tenant_bucket — a bounded t00..tNN set), never
    # raw client-supplied tenant strings.
    "tenant",
    # Elastic training: values are exactly {"grow", "shrink"}
    # (parallel/reshard.ReshardStats.direction).
    "direction",
    # Progressive delivery: values are spec.versions[].name — at most
    # two per service (incumbent + candidate, validate_versions), plus
    # the literal "shadow" fallback for an unnamed mirror target.
    "version",
    # Flash-crowd cold start: values are exactly {"peer", "checkpoint",
    # "init"} (serving/server.py record_weight_pull).
    "source",
    # Birth phase breakdown: values are exactly {"weights", "compile",
    # "first_token"} (InferenceEngine.cold_start keys).
    "phase",
    # Self-tuning engine: trial terminal states are a closed enum
    # (succeeded/failed/preempted/early_stopped), policies are the
    # tuning/suggestions.py _ALGORITHMS registry, and scenario values
    # come from the fixed serving/scenarios.py registry.
    "state", "policy", "scenario",
})

_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")
_BAD_UNITS = ("_ms", "_msec", "_msecs", "_secs", "_sec", "_kb", "_mb")
_REGISTRY_METHODS = {"counter", "gauge", "histogram"}


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _check(ctx: FileContext):
    is_renderer = ctx.relpath.endswith(EXEMPT_PATHS)
    for node in ast.walk(ctx.tree):
        if (not is_renderer and isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and "# TYPE" in node.value):
            yield ("metrics-type-literal", node.lineno, "",
                   "'# TYPE' literal outside observability/metrics.py "
                   "— render through the shared MetricRegistry/"
                   "type_line(), never hand-roll the text format")
        if not isinstance(node, ast.Call):
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr not in _REGISTRY_METHODS:
            continue
        recv = (_dotted(node.func.value) or "").lower()
        if "registr" not in recv and "metrics" not in recv:
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        name = node.args[0].value
        kind = node.func.attr
        line = node.lineno
        if not _NAME_RE.match(name):
            yield ("metrics-name-convention", line, "",
                   f"metric name {name!r} is not snake_case "
                   "{subsystem}_{name}[_{unit}]")
        else:
            if name.split("_", 1)[0] not in SUBSYSTEMS:
                yield ("metrics-name-convention", line, "",
                       f"metric {name!r} has unknown subsystem prefix "
                       f"{name.split('_', 1)[0]!r} (known: "
                       f"{', '.join(SUBSYSTEMS)})")
            if kind == "counter" and not name.endswith("_total"):
                yield ("metrics-name-convention", line, "",
                       f"counter {name!r} must end in _total")
            if kind != "counter" and name.endswith("_total"):
                yield ("metrics-name-convention", line, "",
                       f"{kind} {name!r} must not end in _total "
                       "(reserved for counters)")
            if any(name.endswith(u) for u in _BAD_UNITS):
                yield ("metrics-name-convention", line, "",
                       f"metric {name!r} uses an abbreviated unit — "
                       "spell out _seconds/_bytes (base units)")
        for kw in node.keywords:
            if kw.arg != "labels":
                continue
            for elt in ast.walk(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str):
                    if elt.value == "le":
                        yield ("metrics-label-vocab", line, "",
                               "label 'le' is reserved for histogram "
                               "buckets")
                    elif elt.value not in LABEL_VOCAB:
                        yield ("metrics-label-vocab", line, "",
                               f"label {elt.value!r} outside the "
                               "bounded vocabulary "
                               f"({', '.join(sorted(LABEL_VOCAB))}) — "
                               "extend LABEL_VOCAB deliberately "
                               "instead of ad hoc")


register(Checker(
    name="metrics-exposition",
    rules=("metrics-type-literal", "metrics-name-convention",
           "metrics-label-vocab"),
    doc="Single-renderer invariant, metric naming convention, bounded "
        "label vocabulary",
    fn=_check,
))
