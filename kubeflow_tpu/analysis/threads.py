"""Thread-lifecycle checker.

Every ``threading.Thread(...)`` started in the tree must make two
explicit choices, or the reviewer can't tell leak from design:

- ``thread-no-daemon``: the constructor passes ``daemon=`` (or the
  bound name gets a ``.daemon =`` assignment before ``start()``).
  Python's default (inherit the creator's daemonness) is how shutdown
  hangs ship.

- ``thread-no-join``: somewhere in the module there is a reachable way
  for the thread to END — a ``.join()`` on the name/attribute the
  thread is bound to, or (for daemon loops) a recognizable stop signal:
  a ``threading.Event`` that gets ``.set()``, a ``*stop*``/``*closed*``
  flag assigned truthy, a server ``.shutdown()``/``.close()`` call, or
  a ``serve_forever`` target (whose stop IS ``shutdown()``, often owned
  by the caller holding the returned server). A non-daemon thread must
  have a join path; "the process will exit eventually" is not one.
"""

from __future__ import annotations

import ast

from kubeflow_tpu.analysis.core import Checker, FileContext, register


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func) or ""
    return name in ("threading.Thread", "Thread") or name.endswith(
        ".Thread")


def _module_stop_paths(tree: ast.AST) -> dict[str, bool]:
    """Signals that some thread in this module can be told to stop."""
    event_attrs: set[str] = set()
    facts = {"event_set": False, "stop_flag": False, "shutdown": False}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            ctor = _dotted(node.value.func) or ""
            if ctor.rsplit(".", 1)[-1] == "Event":
                for t in node.targets:
                    name = _dotted(t)
                    if name:
                        event_attrs.add(name.rsplit(".", 1)[-1])
        if isinstance(node, ast.Assign):
            for t in node.targets:
                name = (_dotted(t) or "").rsplit(".", 1)[-1].lower()
                if ("stop" in name or "closed" in name
                        or "shutdown" in name):
                    facts["stop_flag"] = True
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf == "set" and name.split(".")[-2:-1] and \
                    name.split(".")[-2] in event_attrs:
                facts["event_set"] = True
            if leaf in ("shutdown", "close", "stop"):
                facts["shutdown"] = True
    return facts


def _joined_names(tree: ast.AST) -> set[str]:
    """Leaf names ``X`` for every ``X.join(...)`` / ``self.X.join(...)``
    in the module (thread bindings are matched by leaf name)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            recv = _dotted(node.func.value)
            if recv:
                out.add(recv.rsplit(".", 1)[-1])
    return out


def _daemon_assigned(tree: ast.AST, binding: str | None) -> bool:
    if binding is None:
        return False
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                name = _dotted(t) or ""
                if name.endswith(f"{binding}.daemon"):
                    return True
    return False


def _check(ctx: FileContext):
    stop = _module_stop_paths(ctx.tree)
    joined = _joined_names(ctx.tree)
    # Thread ctor sites with their binding (assignment target leaf name,
    # or None for anonymous ``threading.Thread(...).start()``).
    for node in ast.walk(ctx.tree):
        binding = None
        call = None
        if isinstance(node, ast.Assign) and _is_thread_ctor(node.value):
            call = node.value
            for t in node.targets:
                name = _dotted(t)
                if name:
                    binding = name.rsplit(".", 1)[-1]
        elif isinstance(node, ast.Call) and _is_thread_ctor(node):
            parent_handled = False  # assignments handled above
            call = node
            for holder in ast.walk(ctx.tree):
                if isinstance(holder, ast.Assign) and holder.value is node:
                    parent_handled = True
            if parent_handled:
                continue
        if call is None:
            continue
        symbol = _enclosing(ctx.tree, call)
        kwargs = {kw.arg: kw.value for kw in call.keywords}
        daemon = kwargs.get("daemon")
        if daemon is None and not _daemon_assigned(ctx.tree, binding):
            yield ("thread-no-daemon", call.lineno, symbol,
                   "threading.Thread without an explicit daemon= "
                   "choice — inherited daemonness is how shutdown "
                   "hangs ship")
        target = kwargs.get("target")
        target_name = (_dotted(target) or "") if target is not None \
            else ""
        serve_forever = target_name.endswith("serve_forever")
        has_join = binding is not None and binding in joined
        daemon_true = (isinstance(daemon, ast.Constant)
                       and daemon.value is True)
        has_stop = (stop["event_set"] or stop["stop_flag"]
                    or stop["shutdown"] or serve_forever)
        if not has_join and not (daemon_true and has_stop):
            yield ("thread-no-join", call.lineno, symbol,
                   "started thread has no reachable join()/stop path "
                   "in this module (join the binding, or daemon=True "
                   "plus an Event/stop-flag/shutdown signal)")


def _enclosing(tree: ast.AST, target: ast.AST) -> str:
    """Qualname of the def/class lexically containing ``target``."""
    path: list[str] = []

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if child is target:
                path.extend(stack)
                return True
            name = getattr(child, "name", None) if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef)) else None
            if visit(child, stack + [name] if name else stack):
                return True
        return False

    visit(tree, [])
    return ".".join(path)


register(Checker(
    name="thread-lifecycle",
    rules=("thread-no-daemon", "thread-no-join"),
    doc="Threads must choose daemon= explicitly and have a reachable "
        "join()/stop path",
    fn=_check,
))
