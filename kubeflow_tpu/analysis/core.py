"""tpu-lint framework: findings, suppressions, baseline, driver.

The checker modules are pure AST visitors; this module owns everything
around them — the :class:`Finding` record, the per-line suppression
grammar (reasons are MANDATORY: an excuse-free suppression is itself a
finding), the incremental-adoption :class:`Baseline` (entries that stop
firing are *stale* and fail CI, so the baseline only ever shrinks), and
the path walker that feeds each file to every registered checker.

Checkers register a :class:`Checker` in :data:`ALL_CHECKERS`; each owns
a disjoint set of rule names and yields raw ``(rule, line, symbol,
message)`` tuples from ``fn(ctx)``. The driver attaches file identity
and applies suppressions, so checkers never deal with either.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Iterator

# ``# tpu-lint: disable=rule-a,rule-b -- reason`` — the reason is part
# of the grammar, not a convention: a match without one is reported as
# bad-suppression and suppresses nothing.
_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*disable=(?P<rules>[a-z0-9,-]+)"
    r"(?:\s+--\s*(?P<reason>\S.*))?")

# Rule name for malformed/reason-less suppressions. Not suppressible.
BAD_SUPPRESSION = "bad-suppression"


@dataclass(frozen=True)
class Finding:
    """One checker hit, anchored to a file/line/symbol."""

    rule: str
    path: str          # repo-relative (or as-given) posix path
    line: int
    symbol: str        # enclosing ``Class.method`` / ``function`` / ""
    message: str

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line-number-insensitive so routine edits
        above a baselined finding don't churn the baseline."""
        return (self.rule, self.path, self.symbol)

    def __str__(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{where}: {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message}


@dataclass(frozen=True)
class Checker:
    """One named checker owning one or more rule names.

    ``fn(ctx)`` yields ``(rule, line, symbol, message)`` tuples; the
    driver wraps them into :class:`Finding` and applies suppressions.
    """

    name: str
    rules: tuple[str, ...]
    doc: str
    fn: Callable[["FileContext"], Iterable[tuple[str, int, str, str]]]


@dataclass
class _Suppression:
    rules: tuple[str, ...]
    reason: str
    line: int          # the line the suppression comment sits on
    target: int        # the line it suppresses
    used: bool = False


class FileContext:
    """Everything a checker may look at for one file: source, AST, and
    the pre-parsed suppression table."""

    def __init__(self, path: Path, relpath: str, source: str,
                 tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.suppressions = _parse_suppressions(self.lines)


def _parse_suppressions(lines: list[str]) -> list[_Suppression]:
    out: list[_Suppression] = []
    for lineno, line in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m is None:
            continue
        rules = tuple(r for r in m.group("rules").split(",") if r)
        reason = (m.group("reason") or "").strip()
        # A comment-only line suppresses the NEXT line; a trailing
        # comment suppresses its own line.
        target = lineno + 1 if line.lstrip().startswith("#") else lineno
        out.append(_Suppression(rules=rules, reason=reason,
                                line=lineno, target=target))
    return out


# -- checker registry ---------------------------------------------------

ALL_CHECKERS: list[Checker] = []


def register(checker: Checker) -> Checker:
    taken = {r for c in ALL_CHECKERS for r in c.rules}
    dup = taken.intersection(checker.rules)
    if dup:
        raise ValueError(f"rules {sorted(dup)} already registered")
    ALL_CHECKERS.append(checker)
    return checker


def all_rules() -> list[str]:
    return sorted(r for c in ALL_CHECKERS for r in c.rules)


def checker_for_rule(rule: str) -> Checker | None:
    for c in ALL_CHECKERS:
        if rule in c.rules:
            return c
    return None


def _load_checkers() -> None:
    """Import the checker modules (each registers itself on import).
    Deferred so ``core`` carries no import cycle with them."""
    if ALL_CHECKERS:
        return
    from kubeflow_tpu.analysis import (  # noqa: F401 — import registers
        exposition,
        jax_hygiene,
        locks,
        resources,
        threads,
    )


# -- driver -------------------------------------------------------------

@dataclass
class FileResult:
    """Findings for one file, post-suppression."""

    relpath: str
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)


def analyze_file(path: Path, relpath: str | None = None,
                 rules: set[str] | None = None) -> FileResult:
    """Run every registered checker over one file. ``rules`` narrows to
    a subset (CLI ``--rules``); suppression bookkeeping still runs so a
    reason-less suppression is reported regardless of the subset."""
    _load_checkers()
    rel = relpath if relpath is not None else path.as_posix()
    result = FileResult(relpath=rel)
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        # The style tier (utils.lint E999) owns syntax errors; here it
        # just means no semantic analysis is possible.
        result.findings.append(Finding(
            rule="parse-error", path=rel, line=e.lineno or 0, symbol="",
            message=f"file does not parse: {e.msg}"))
        return result
    ctx = FileContext(path, rel, source, tree)
    raw: list[Finding] = []
    seen: set[Finding] = set()
    for checker in ALL_CHECKERS:
        if rules is not None and not rules.intersection(checker.rules):
            continue
        for rule, line, symbol, message in checker.fn(ctx):
            if rules is not None and rule not in rules:
                continue
            finding = Finding(rule=rule, path=rel, line=line,
                              symbol=symbol, message=message)
            if finding not in seen:  # e.g. one expr read twice
                seen.add(finding)
                raw.append(finding)
    by_target: dict[int, list[_Suppression]] = {}
    for sup in ctx.suppressions:
        by_target.setdefault(sup.target, []).append(sup)
    for finding in raw:
        sup = next(
            (s for s in by_target.get(finding.line, ())
             if finding.rule in s.rules), None)
        if sup is None:
            result.findings.append(finding)
        elif not sup.reason:
            sup.used = True
            result.findings.append(finding)
        else:
            sup.used = True
            result.suppressed.append(finding)
    if rules is None or BAD_SUPPRESSION in rules:
        for sup in ctx.suppressions:
            if not sup.reason:
                result.findings.append(Finding(
                    rule=BAD_SUPPRESSION, path=rel, line=sup.line,
                    symbol="",
                    message=("suppression must carry a reason: "
                             "# tpu-lint: disable=<rule> -- <why>")))
    return result


def iter_python_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def analyze_paths(paths: Iterable[Path], root: Path | None = None,
                  rules: set[str] | None = None) -> list[FileResult]:
    """Analyze every ``*.py`` under ``paths``; relpaths are taken
    relative to ``root`` (default: cwd) when possible so findings and
    baselines are machine-independent."""
    base = root or Path.cwd()
    out = []
    for f in iter_python_files(paths):
        try:
            rel = f.resolve().relative_to(base.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        out.append(analyze_file(f, rel, rules))
    return out


# -- baseline -----------------------------------------------------------

class Baseline:
    """Checked-in set of accepted findings, keyed line-insensitively.

    ``apply`` splits current findings into new-vs-baselined and reports
    the *stale* entries — baseline keys that no longer fire. Stale
    entries fail CI (``ci/static_analysis.sh``): the baseline is a
    ratchet that only shrinks, never a place findings quietly live
    forever."""

    VERSION = 1

    def __init__(self, entries: Iterable[dict] = ()):
        self.entries = [dict(e) for e in entries]

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        data = json.loads(path.read_text())
        if data.get("version") != cls.VERSION:
            raise ValueError(
                f"{path}: unsupported baseline version "
                f"{data.get('version')!r}")
        return cls(data.get("findings", []))

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        seen: dict[tuple, dict] = {}
        for f in findings:
            seen.setdefault(f.key(), {
                "rule": f.rule, "path": f.path, "symbol": f.symbol,
                "message": f.message})
        return cls(seen.values())

    def dump(self) -> str:
        entries = sorted(
            self.entries,
            key=lambda e: (e["path"], e["rule"], e.get("symbol", "")))
        return json.dumps({"version": self.VERSION, "findings": entries},
                          indent=2) + "\n"

    def _keys(self) -> set[tuple[str, str, str]]:
        return {(e["rule"], e["path"], e.get("symbol", ""))
                for e in self.entries}

    def apply(self, findings: Iterable[Finding]
              ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """→ (new findings, baselined findings, stale entries)."""
        keys = self._keys()
        new, old = [], []
        fired: set[tuple] = set()
        for f in findings:
            if f.key() in keys:
                old.append(f)
                fired.add(f.key())
            else:
                new.append(f)
        stale = [e for e in self.entries
                 if (e["rule"], e["path"], e.get("symbol", ""))
                 not in fired]
        return new, old, stale
