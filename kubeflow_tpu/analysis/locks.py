"""Lock-discipline checker: the shipped-bug classes made rules.

Three rules over each module's inferred lock-acquisition structure
(``with self._lock:`` / ``.acquire()``–``.release()`` pairs, with
entry-guard propagation through private ``self._m()`` calls):

- ``lock-blocking-call`` — a call that can block the host (queue
  ``get``/``put`` that can wait, socket/HTTP, thread ``join``,
  ``time.sleep``, untimed ``Event.wait``, ``jax.device_get`` /
  ``block_until_ready`` device syncs, ``jax.device_put`` host→device
  transfers — the weight-swap buffer install class) while any lock is
  held. This is the PR-9 stall as a rule: an import held the prefix
  lock across the state-lock device wait and froze the scheduler's
  pop path.
  ``Condition.wait`` on the *held* condition is exempt — waiting
  releases it (the false-positive fixture the checker must pass).

- ``lock-order-cycle`` — two locks acquired in both nesting orders
  anywhere in the module (classic deadlock), or a non-reentrant lock
  re-acquired while already held (self-deadlock).

- ``lock-inconsistent-guard`` — one attribute written under a lock at
  some sites but not others, or written consistently under a lock and
  read elsewhere without it: the PR-4 torn-metrics class (and the
  PR-8 early-table-arm repro lands here — the block-table row armed in
  the pop path under a different guard than its owning dispatch).
"""

from __future__ import annotations

import ast
from collections import defaultdict
from dataclasses import dataclass, field

from kubeflow_tpu.analysis.core import Checker, FileContext, register

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "setdefault", "sort", "reverse",
}
_INIT_METHODS = {"__init__", "__post_init__", "__enter__"}


def _dotted(node: ast.AST) -> str | None:
    """``self._alloc.free`` → ``"self._alloc.free"`` (None when the
    chain bottoms out in anything but a Name)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` → ``X`` (exactly one attribute hop)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _write_root(target: ast.AST) -> str | None:
    """The ``self`` attribute a store ultimately mutates:
    ``self.X = / self.X[...] = / self.X.y = `` all root at ``X``."""
    node = target
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        name = _self_attr(node)
        if name is not None:
            return name
        node = node.value
    return None


@dataclass
class _Site:
    line: int
    symbol: str
    guards: frozenset  # lock names held lexically at the site
    method: str        # enclosing class method ("" at module level)


@dataclass
class _ClassModel:
    name: str
    locks: dict[str, str] = field(default_factory=dict)   # attr → kind
    queues: dict[str, bool] = field(default_factory=dict)  # attr → bounded
    threads: set[str] = field(default_factory=set)
    events: set[str] = field(default_factory=set)
    containers: set[str] = field(default_factory=set)
    writes: dict[str, list[_Site]] = field(
        default_factory=lambda: defaultdict(list))
    reads: dict[str, list[_Site]] = field(
        default_factory=lambda: defaultdict(list))
    # method → [(caller_method, guards_at_call)]
    calls: dict[str, list[tuple[str, frozenset]]] = field(
        default_factory=lambda: defaultdict(list))
    # method → locks it acquires directly in its own body
    acquires: dict[str, set[str]] = field(
        default_factory=lambda: defaultdict(set))
    methods: set[str] = field(default_factory=set)


def _lock_kind(value: ast.AST) -> str | None:
    """``threading.Lock()`` / ``Lock()`` → kind, else None."""
    if isinstance(value, ast.Call):
        name = _dotted(value.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _LOCK_CTORS:
            return leaf.lower()
        if leaf == "Event":
            return "event"
    return None


def _queue_bounded(value: ast.AST) -> bool | None:
    """``queue.Queue(...)``-shaped constructor → is it bounded? None
    when the value is not a queue constructor."""
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func) or ""
    if name.rsplit(".", 1)[-1] not in ("Queue", "LifoQueue",
                                       "PriorityQueue", "SimpleQueue"):
        return None
    maxsize = None
    if value.args:
        maxsize = value.args[0]
    for kw in value.keywords:
        if kw.arg == "maxsize":
            maxsize = kw.value
    if maxsize is None:
        return False
    if isinstance(maxsize, ast.Constant) and not maxsize.value:
        return False
    return True


def _is_thread_ctor(value: ast.AST) -> bool:
    return (isinstance(value, ast.Call)
            and (_dotted(value.func) or "").endswith("Thread"))


_CONTAINER_CTORS = {"dict", "set", "list", "deque", "defaultdict",
                    "OrderedDict", "Counter"}


def _is_container(value: ast.AST) -> bool:
    """Literals/constructors of plain mutable containers — the attrs
    whose ``.append()``/``.add()``/… calls count as writes. Arbitrary
    objects (a PrefixCache, a client) own their own thread-safety; a
    method call on them is not a write to the attribute."""
    if isinstance(value, (ast.Dict, ast.Set, ast.List, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        leaf = (_dotted(value.func) or "").rsplit(".", 1)[-1]
        return leaf in _CONTAINER_CTORS
    return False


def _assign_targets(node) -> list[ast.AST]:
    """Assignment targets with tuple/list unpacking flattened."""
    targets = (node.targets if isinstance(node, ast.Assign)
               else [node.target])
    out: list[ast.AST] = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            out.extend(t.elts)
        else:
            out.append(t)
    return out


class _LockChecker:
    """Per-file analysis driver; produces raw finding tuples."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: list[tuple[int, str, str, str]] = []
        self.module_locks: dict[str, str] = {}
        # (lockA → lockB) nesting edges with a representative site.
        self.edges: dict[tuple[str, str], _Site] = {}

    def run(self):
        for node in self.ctx.tree.body:
            if isinstance(node, ast.Assign) and _lock_kind(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks[t.id] = _lock_kind(node.value)
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node)
        # Module-level functions (not inside classes).
        for node in self.ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                model = _ClassModel(name="")
                self._walk_block(node.body, frozenset(), node.name,
                                 "", model, {})
        self._report_cycles()
        for line, rule, symbol, message in self.findings:
            yield rule, line, symbol, message

    # -- per-class ------------------------------------------------------

    def _check_class(self, cls: ast.ClassDef):
        model = _ClassModel(name=cls.name)
        methods: dict[str, ast.FunctionDef] = {}
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[node.name] = node
                model.methods.add(node.name)
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        if sub.value is None:
                            continue
                        for t in _assign_targets(sub):
                            attr = _self_attr(t)
                            if attr is None:
                                continue
                            kind = _lock_kind(sub.value)
                            if kind == "event":
                                model.events.add(attr)
                            elif kind is not None:
                                model.locks[attr] = kind
                            bounded = _queue_bounded(sub.value)
                            if bounded is not None:
                                model.queues[attr] = bounded
                            if _is_thread_ctor(sub.value):
                                model.threads.add(attr)
                            if _is_container(sub.value):
                                model.containers.add(attr)
        for name, node in methods.items():
            local_threads = {
                t.id for sub in ast.walk(node)
                if isinstance(sub, ast.Assign) and _is_thread_ctor(sub.value)
                for t in sub.targets if isinstance(t, ast.Name)}
            self._walk_block(node.body, frozenset(), f"{cls.name}.{name}",
                             name, model, local_threads)
        entry = self._entry_guards(model)
        self._apply_entry_guards(model, entry)
        self._guard_rules(model)

    def _entry_guards(self, model: _ClassModel) -> dict[str, frozenset]:
        """Locks provably held at EVERY intra-class call site of each
        private method (public methods are callable from anywhere, so
        their entry set is empty). Optimistic fixpoint — private
        methods with call sites start at ⊤ (all locks) and shrink to
        the intersection — so a recursive helper always called under a
        lock (FakeApiServer._cascade_delete under its RLock) keeps the
        guard instead of losing it to its own recursive call site."""
        top = frozenset(
            [f"self.{a}" for a in model.locks] + list(self.module_locks))
        entry = {}
        for m in model.methods:
            private = m.startswith("_") and not m.startswith("__")
            entry[m] = top if private and model.calls.get(m) \
                else frozenset()
        for _ in range(20):
            changed = False
            for m in model.methods:
                sites = model.calls.get(m)
                if not sites or not entry[m]:
                    continue
                if not m.startswith("_") or m.startswith("__"):
                    continue
                new = frozenset.intersection(
                    *[guards | entry.get(caller, frozenset())
                      for caller, guards in sites])
                if new != entry[m]:
                    entry[m] = new
                    changed = True
            if not changed:
                break
        return entry

    def _apply_entry_guards(self, model: _ClassModel,
                            entry: dict[str, frozenset]):
        for sites in list(model.writes.values()) + list(
                model.reads.values()):
            for site in sites:
                site.guards = site.guards | entry.get(site.method,
                                                      frozenset())
        # Entry guards also complete the nesting edges: a method that
        # acquires L and is only ever called under G nests G → L.
        for m, acquired in model.acquires.items():
            for held in entry.get(m, frozenset()):
                for lock in acquired:
                    self._edge(held, lock, _Site(0, m, frozenset(), m))

    # -- statement walker ----------------------------------------------

    def _lock_name(self, expr: ast.AST, model: _ClassModel) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and attr in model.locks:
            return f"self.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return expr.id
        return None

    def _lock_kind_of(self, lock: str, model: _ClassModel) -> str:
        if lock.startswith("self."):
            return model.locks.get(lock[5:], "lock")
        return self.module_locks.get(lock, "lock")

    def _edge(self, a: str, b: str, site: _Site):
        if (a, b) not in self.edges:
            self.edges[(a, b)] = site

    def _walk_block(self, stmts: list[ast.stmt], held: frozenset,
                    symbol: str, method: str, model: _ClassModel,
                    local_threads: set[str]):
        held = frozenset(held)
        for stmt in stmts:
            held = self._walk_stmt(stmt, held, symbol, method, model,
                                   local_threads)

    def _walk_stmt(self, stmt: ast.stmt, held: frozenset, symbol: str,
                   method: str, model: _ClassModel,
                   local_threads: set[str]) -> frozenset:
        """Process one statement under ``held``; returns the held set
        for the NEXT statement (``.acquire()``/``.release()`` mutate
        it, ``with`` does not outlive its body)."""
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs inherit the held set at their definition site:
            # in this tree they are inline helpers called under the
            # same lock (the BanditStats.mean false-positive fixture).
            # A helper stashed for deferred execution may over-report;
            # that is what suppressions are for.
            self._walk_block(stmt.body, held,
                             f"{symbol}.{stmt.name}", method, model,
                             local_threads)
            return held
        if isinstance(stmt, ast.ClassDef):
            return held
        if isinstance(stmt, ast.With):
            acquired = []
            for item in stmt.items:
                self._scan_exprs([item.context_expr], held, symbol,
                                 method, model, local_threads)
                lock = self._lock_name(item.context_expr, model)
                if lock is not None:
                    kind = self._lock_kind_of(lock, model)
                    if lock in held and kind != "rlock":
                        self._finding(
                            stmt.lineno, "lock-order-cycle", symbol,
                            f"{lock} re-acquired while already held "
                            "(self-deadlock on a non-reentrant lock)")
                    for other in held:
                        self._edge(other, lock,
                                   _Site(stmt.lineno, symbol,
                                         held, method))
                    if method:
                        model.acquires[method].add(lock)
                    acquired.append(lock)
            self._walk_block(stmt.body, held | frozenset(acquired),
                             symbol, method, model, local_threads)
            return held
        # Expression parts of compound statements, then their blocks.
        blocks: list[list[ast.stmt]] = []
        exprs: list[ast.AST] = []
        if isinstance(stmt, (ast.If, ast.While)):
            exprs.append(stmt.test)
            blocks = [stmt.body, stmt.orelse]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            exprs += [stmt.target, stmt.iter]
            blocks = [stmt.body, stmt.orelse]
        elif isinstance(stmt, ast.Try):
            blocks = [stmt.body, stmt.orelse, stmt.finalbody]
            for handler in stmt.handlers:
                blocks.append(handler.body)
        else:
            exprs.append(stmt)
        held_out = self._scan_exprs(exprs, held, symbol, method, model,
                                    local_threads)
        for block in blocks:
            self._walk_block(block, held, symbol, method, model,
                             local_threads)
        return held_out

    def _scan_exprs(self, exprs: list[ast.AST], held: frozenset,
                    symbol: str, method: str, model: _ClassModel,
                    local_threads: set[str]) -> frozenset:
        write_nodes: set[int] = set()
        for expr in exprs:
            for node in ast.walk(expr):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    for t in _assign_targets(node):
                        root = _write_root(t)
                        if root is not None and method:
                            self._record_access(
                                model.writes, root, node.lineno, symbol,
                                held, method, model)
                            for sub in ast.walk(t):
                                write_nodes.add(id(sub))
        held_out = held
        for expr in exprs:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    held_out = self._scan_call(
                        node, held, held_out, symbol, method, model,
                        local_threads, write_nodes)
                attr = _self_attr(node)
                if (attr is not None and method
                        and isinstance(node.ctx, ast.Load)
                        and id(node) not in write_nodes):
                    self._record_access(model.reads, attr, node.lineno,
                                        symbol, held, method, model)
        return held_out

    def _record_access(self, table, attr, line, symbol, held, method,
                       model: _ClassModel):
        if attr in model.locks or attr in model.events:
            return
        table[attr].append(_Site(line, symbol, held, method))

    def _scan_call(self, node: ast.Call, held: frozenset,
                   held_out: frozenset, symbol: str, method: str,
                   model: _ClassModel, local_threads: set[str],
                   write_nodes: set[int]) -> frozenset:
        func = node.func
        dotted = _dotted(func) or ""
        leaf = dotted.rsplit(".", 1)[-1]
        recv = func.value if isinstance(func, ast.Attribute) else None
        recv_dotted = _dotted(recv) if recv is not None else None
        # .acquire()/.release() on a known lock: explicit span.
        lock = self._lock_name(recv, model) if recv is not None else None
        if lock is not None and leaf == "acquire":
            for other in held_out:
                self._edge(other, lock,
                           _Site(node.lineno, symbol, held_out, method))
            if method:
                model.acquires[method].add(lock)
            return held_out | {lock}
        if lock is not None and leaf == "release":
            return held_out - {lock}
        # Mutator method call on a container-valued self.X == a write
        # to X (non-container objects own their own thread-safety).
        if (recv is not None and leaf in _MUTATORS and method):
            root = _write_root(func)
            if root is not None and root in model.containers:
                self._record_access(model.writes, root, node.lineno,
                                    symbol, held, method, model)
                for sub in ast.walk(recv):
                    write_nodes.add(id(sub))
        # Intra-class call: record for entry-guard/edge propagation.
        if (recv is not None and isinstance(recv, ast.Name)
                and recv.id == "self" and method):
            model.calls[leaf].append((method, held))
        if held:
            blocked = self._blocking_reason(node, dotted, leaf, recv,
                                            recv_dotted, held, model,
                                            local_threads)
            if blocked:
                locks_held = ", ".join(sorted(held))
                self._finding(
                    node.lineno, "lock-blocking-call", symbol,
                    f"{blocked} while holding {locks_held} — a blocked "
                    "holder stalls every thread contending for the "
                    "lock (PR-9 bug class)")
        return held_out

    def _blocking_reason(self, node: ast.Call, dotted: str, leaf: str,
                         recv, recv_dotted, held: frozenset,
                         model: _ClassModel,
                         local_threads: set[str]) -> str | None:
        kwargs = {kw.arg for kw in node.keywords}
        if dotted in ("time.sleep",) or leaf == "sleep" and \
                (recv_dotted or "") == "time":
            return "time.sleep()"
        if dotted in ("jax.device_get", "jax.block_until_ready"):
            return f"device sync {dotted}()"
        if dotted == "jax.device_put":
            # The host→device transfer behind a weight-swap buffer
            # install: issuing it under a held lock serializes every
            # contending thread behind the whole copy. The zero-drain
            # pattern stages buffers OUTSIDE the lock and swaps the
            # pointer under it.
            return "host-to-device transfer jax.device_put()"
        if leaf == "block_until_ready":
            return "device sync .block_until_ready()"
        if leaf in ("urlopen", "create_connection"):
            return f"network call {leaf}()"
        if leaf in ("recv", "accept") and any(
                s in (recv_dotted or "").lower()
                for s in ("sock", "conn")):
            return f"socket .{leaf}()"
        if leaf == "join":
            attr = _self_attr(recv) if recv is not None else None
            is_thread = (attr in model.threads
                         or (isinstance(recv, ast.Name)
                             and recv.id in local_threads))
            if is_thread:
                return "thread .join()"
        if leaf == "result" and not isinstance(recv, ast.Constant):
            return "handle/future .result() wait"
        if leaf == "wait" and not node.args and not kwargs:
            lock = (self._lock_name(recv, model)
                    if recv is not None else None)
            if lock is not None and lock in held and \
                    self._lock_kind_of(lock, model) == "condition":
                return None  # Condition.wait releases the held lock
            return "untimed .wait()"
        if leaf in ("get", "put"):
            attr = _self_attr(recv) if recv is not None else None
            if attr in model.queues:
                if "timeout" in kwargs:
                    return None
                for kw in node.keywords:
                    if (kw.arg == "block"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is False):
                        return None
                if (node.args and isinstance(node.args[-1], ast.Constant)
                        and node.args[-1].value is False):
                    return None
                if leaf == "put" and not model.queues[attr]:
                    return None  # unbounded queue: put never blocks
                return f"queue .{leaf}() that can block"
        return None

    # -- guard-consistency rules ---------------------------------------

    def _guard_rules(self, model: _ClassModel):
        for attr, writes in sorted(model.writes.items()):
            sites = [s for s in writes if s.method not in _INIT_METHODS]
            if not sites:
                continue
            guard_sets = [s.guards for s in sites]
            common = frozenset.intersection(*guard_sets)
            if len(sites) >= 2 and not common:
                counts: dict[str, int] = defaultdict(int)
                for g in guard_sets:
                    for lock in g:
                        counts[lock] += 1
                if counts:
                    lock = max(sorted(counts), key=lambda k: counts[k])
                    n = counts[lock]
                    for site in sites:
                        if lock not in site.guards:
                            self._finding(
                                site.line, "lock-inconsistent-guard",
                                site.symbol,
                                f"self.{attr} is written under {lock} at "
                                f"{n} of {len(sites)} sites but not here "
                                "— torn/lost updates (PR-4/PR-8 class)")
                continue
            if common:
                reads = [s for s in model.reads.get(attr, ())
                         if s.method not in _INIT_METHODS]
                lock = sorted(common)[0]
                for site in reads:
                    if not common & site.guards:
                        self._finding(
                            site.line, "lock-inconsistent-guard",
                            site.symbol,
                            f"self.{attr} is always written under "
                            f"{lock} but read here without it — torn "
                            "read (PR-4 class)")

    def _report_cycles(self):
        seen = set()
        for (a, b), site in sorted(self.edges.items(),
                                   key=lambda kv: kv[1].line):
            if a == b or (b, a) not in self.edges or (b, a) in seen:
                continue
            seen.add((a, b))
            other = self.edges[(b, a)]
            self._finding(
                site.line or other.line, "lock-order-cycle", site.symbol,
                f"{a} and {b} are acquired in both orders (here "
                f"{a}→{b}; {b}→{a} at line {other.line}) — deadlock "
                "risk")

    def _finding(self, line, rule, symbol, message):
        self.findings.append((line, rule, symbol, message))


def _check(ctx: FileContext):
    checker = _LockChecker(ctx)
    for rule, line, symbol, message in checker.run():
        yield rule, line, symbol, message


register(Checker(
    name="lock-discipline",
    rules=("lock-blocking-call", "lock-order-cycle",
           "lock-inconsistent-guard"),
    doc="Lock-acquisition graph: blocking calls under locks, order "
        "cycles, inconsistently guarded attributes",
    fn=_check,
))
