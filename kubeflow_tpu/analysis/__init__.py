"""tpu-lint — AST-based semantic analysis gating CI.

The style tier (:mod:`kubeflow_tpu.utils.lint`) keeps the tree
flake8-clean; this package is the semantic tier — the ``go vet`` /
race-detector analogue the source platform's Go layers get for free and
our Python/JAX reproduction did not. Every checker targets a bug class
this repo has actually shipped and then fixed:

- **lock-discipline** (:mod:`.locks`): the PR-9 stall (a prefix lock
  held across a state-lock device wait), PR-4 torn metric reads, and
  deadlock-shaped lock-order cycles;
- **thread-lifecycle** (:mod:`.threads`): threads without an explicit
  ``daemon=`` choice or any reachable join/stop path;
- **resource-pairing** (:mod:`.resources`): allocator ``alloc``/
  ``share`` without a ``free`` on the exception path — the KV-block
  leak class;
- **JAX hygiene** (:mod:`.jax_hygiene`): host syncs and impure calls
  inside jitted/scanned/shard_mapped functions;
- **metrics exposition** (:mod:`.exposition`): the single-renderer
  invariant plus metric-name and label-vocabulary conventions,
  absorbing the old grep gate in ``ci/metrics_lint.sh``.

``python -m kubeflow_tpu.analysis <paths>`` runs the suite;
``ci/static_analysis.sh`` gates release-tag on it. Intentional
violations carry per-line suppressions with mandatory reasons
(``# tpu-lint: disable=<rule> -- <why>``); a checked-in findings
baseline (``ci/tpu_lint_baseline.json``) makes adoption incremental
without letting new findings in. See docs/static-analysis.md.
"""

from kubeflow_tpu.analysis.core import (
    ALL_CHECKERS,
    Baseline,
    Finding,
    all_rules,
    analyze_paths,
    checker_for_rule,
)

__all__ = [
    "ALL_CHECKERS",
    "Baseline",
    "Finding",
    "all_rules",
    "analyze_paths",
    "checker_for_rule",
]
