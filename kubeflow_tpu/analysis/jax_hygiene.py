"""JAX-hygiene checker: no host syncs or impurity inside traced code.

Traced contexts are found structurally: functions decorated
``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` (static argnames
parsed from the decorator), and local functions passed to
``lax.scan`` / ``lax.cond`` / ``lax.while_loop`` / ``shard_map`` /
``jax.jit(f)``. Inside them:

- ``jit-host-sync``: ``.item()`` / ``.tolist()`` / ``np.asarray`` /
  ``np.array`` / ``jax.device_get`` / ``.block_until_ready()`` — each
  forces a device→host transfer at trace time (or fails under real
  tracing) and silently serializes the dispatch pipeline;

- ``jit-impure-call``: ``print`` / ``time.*`` / stdlib ``random.*`` /
  ``np.random.*`` / ``open`` — runs once at trace time, then never
  again; the classic "my debug print only fired once" and
  "every retrace reseeds differently" traps;

- ``jit-traced-branch``: a Python ``if``/``while`` whose test reads a
  *traced* parameter (not listed in ``static_argnames``/``argnums``)
  — under tracing this raises ``TracerBoolConversionError`` or, worse,
  bakes in one branch. ``is None`` / ``is not None`` tests are exempt
  (argument-structure dispatch is static per trace).
"""

from __future__ import annotations

import ast

from kubeflow_tpu.analysis.core import Checker, FileContext, register

_HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "jax.device_get"}
_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")
_IMPURE_NAMES = {"print", "open", "input"}
_TRACING_WRAPPERS = {"scan", "cond", "while_loop", "fori_loop", "jit",
                     "shard_map", "pmap", "vmap", "grad",
                     "value_and_grad", "checkpoint", "remat"}


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _jit_decorator(fn: ast.FunctionDef) -> tuple[bool, set[str]]:
    """(is jitted, static param names) from the decorator list."""
    for dec in fn.decorator_list:
        name = _dotted(dec) or ""
        if name in ("jax.jit", "jit"):
            return True, set()
        if isinstance(dec, ast.Call):
            cname = _dotted(dec.func) or ""
            if cname in ("jax.jit", "jit"):
                return True, _static_names(dec, fn)
            if cname.endswith("partial"):
                if dec.args and (_dotted(dec.args[0]) or "") in (
                        "jax.jit", "jit"):
                    return True, _static_names(dec, fn)
    return False, set()


def _static_names(call: ast.Call, fn: ast.FunctionDef) -> set[str]:
    params = [a.arg for a in fn.args.args]
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "donate_argnames"):
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str):
                    if kw.arg == "static_argnames":
                        out.add(node.value)
        elif kw.arg in ("static_argnums",):
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, int) and node.value < len(params):
                    out.add(params[node.value])
    return out


def _local_traced_fns(tree: ast.AST) -> set[str]:
    """Names of local ``def``s passed to lax.scan/cond/shard_map/jit —
    traced even without a decorator."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = (_dotted(node.func) or "").rsplit(".", 1)[-1]
        if leaf not in _TRACING_WRAPPERS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                out.add(arg.id)
    return out


def _check_body(fn: ast.FunctionDef, static: set[str], symbol: str):
    params = {a.arg for a in fn.args.args
              if a.arg not in ("self", "cls")}
    traced = params - static
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if name in _HOST_SYNC_CALLS or (
                    isinstance(node.func, ast.Attribute)
                    and leaf in _HOST_SYNC_ATTRS):
                yield ("jit-host-sync", node.lineno, symbol,
                       f"host sync {name or '.' + leaf}() inside a "
                       "traced function — forces a device round-trip "
                       "at trace time or fails under jit")
            elif name in _IMPURE_NAMES or any(
                    name.startswith(p) for p in _IMPURE_PREFIXES):
                yield ("jit-impure-call", node.lineno, symbol,
                       f"impure call {name}() inside a traced function "
                       "— runs at trace time only, not per step")
        if isinstance(node, (ast.If, ast.While)):
            bad = _traced_test_name(node.test, traced)
            if bad is not None:
                yield ("jit-traced-branch", node.lineno, symbol,
                       f"Python branch on traced parameter {bad!r} — "
                       "TracerBoolConversionError under jit (use "
                       "lax.cond/jnp.where, or mark it static)")


def _traced_test_name(test: ast.AST, traced: set[str]) -> str | None:
    # ``x is None`` / ``x is not None`` — structural dispatch, static
    # per trace, legal.
    for node in ast.walk(test):
        if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return None
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in traced:
            return node.id
    return None


def _check(ctx: FileContext):
    traced_names = _local_traced_fns(ctx.tree)

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                symbol = ".".join(stack + [child.name])
                jitted, static = _jit_decorator(child)
                if not jitted and child.name in traced_names:
                    jitted, static = True, set()
                if jitted:
                    yield from _check_body(child, static, symbol)
                else:
                    yield from visit(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, stack + [child.name])
            else:
                yield from visit(child, stack)

    yield from visit(ctx.tree, [])


register(Checker(
    name="jax-hygiene",
    rules=("jit-host-sync", "jit-impure-call", "jit-traced-branch"),
    doc="No host syncs, impure calls, or Python branches on traced "
        "values inside jitted/scanned functions",
    fn=_check,
))
