"""tpu-lint CLI: ``python -m kubeflow_tpu.analysis [paths...]``.

Exit codes: 0 clean (every finding suppressed-with-reason or
baselined, no stale baseline entries), 1 findings or stale baseline,
2 usage error.

Flags:
  --json               machine-readable report on stdout
  --baseline FILE      accept findings recorded in FILE; entries that
                       no longer fire are STALE and fail the run
                       (disable with --no-stale-check)
  --write-baseline FILE  write current findings as the new baseline
                       and exit 0 (adoption bootstrap)
  --rules r1,r2        run only these rules
  --list-rules         print the checker catalog and exit
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from kubeflow_tpu.analysis.core import (
    ALL_CHECKERS,
    Baseline,
    _load_checkers,
    all_rules,
    analyze_paths,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description="tpu-lint: AST-based concurrency, resource-"
                    "lifecycle, JAX-hygiene and exposition analysis")
    parser.add_argument("paths", nargs="*", default=["kubeflow_tpu"])
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--baseline")
    parser.add_argument("--write-baseline")
    parser.add_argument("--no-stale-check", action="store_true")
    parser.add_argument("--rules")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    _load_checkers()
    if args.list_rules:
        for checker in ALL_CHECKERS:
            print(f"{checker.name}: {checker.doc}")
            for rule in checker.rules:
                print(f"  - {rule}")
        return 0

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(all_rules())
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    paths = [Path(p) for p in args.paths]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"no such path: {', '.join(map(str, missing))}",
              file=sys.stderr)
        return 2
    results = analyze_paths(paths, rules=rules)
    findings = [f for r in results for f in r.findings]
    suppressed = [f for r in results for f in r.suppressed]

    if args.write_baseline:
        Path(args.write_baseline).write_text(
            Baseline.from_findings(findings).dump())
        print(f"wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0

    stale: list[dict] = []
    baselined: list = []
    if args.baseline:
        baseline = Baseline.load(Path(args.baseline))
        findings, baselined, stale = baseline.apply(findings)
        if args.no_stale_check:
            stale = []

    if args.json:
        print(json.dumps({
            "files": len(results),
            "findings": [f.to_json() for f in findings],
            "baselined": [f.to_json() for f in baselined],
            "stale_baseline": stale,
            "suppressed": len(suppressed),
        }, indent=2))
    else:
        for f in sorted(findings, key=lambda f: (f.path, f.line)):
            print(f)
        for entry in stale:
            print(f"STALE baseline entry no longer fires: "
                  f"{entry['rule']} {entry['path']} "
                  f"[{entry.get('symbol', '')}] — remove it")
        print(f"tpu-lint: {len(results)} file(s), "
              f"{len(findings)} finding(s), "
              f"{len(baselined)} baselined, "
              f"{len(suppressed)} suppressed, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}")
    return 1 if findings or stale else 0


if __name__ == "__main__":
    raise SystemExit(main())
