"""Resource-pairing checker: allocator claims must be release-safe.

``alloc-no-release``: a function that claims pool resources —
``.alloc(...)`` / ``.share(...)`` on an allocator-shaped receiver
(the name chain contains ``alloc``) — must make the claim impossible
to strand on an exception path. Accepted shapes, in the order real
code uses them:

- a ``.free(...)`` call inside a ``try/finally`` or ``except`` handler
  of the same function (the scratch-blocks pattern);
- ownership transfer: the claimed value (or a name it flows into)
  is stored into non-local state — ``self.X[...] = blocks`` /
  ``entry.blocks = blocks`` — whose owner frees it later (the
  slot-table / trie-entry pattern);
- the claim is returned to the caller (the caller owns it).

This is the PR-4/PR-8 KV-block-leak class: a stream that died between
``alloc`` and slot registration stranded its blocks until the leak
checker — not the allocator — noticed.
"""

from __future__ import annotations

import ast

from kubeflow_tpu.analysis.core import Checker, FileContext, register


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _alloc_recv(node: ast.Call) -> str | None:
    """Receiver chain for ``X.alloc()`` / ``X.share()`` when X looks
    like an allocator (name chain contains ``alloc``)."""
    if not isinstance(node.func, ast.Attribute):
        return None
    if node.func.attr not in ("alloc", "share"):
        return None
    recv = _dotted(node.func.value) or ""
    return recv if "alloc" in recv.lower() else None


class _FnScan:
    """One function's claim/release facts (nested defs NOT descended —
    they are their own functions with their own obligations)."""

    def __init__(self, fn: ast.AST):
        self.claims: list[ast.Call] = []
        self.free_in_cleanup = False
        self.has_return_value = False
        # Name-level dataflow facts, resolved to a fixpoint afterwards:
        # a "claim name" is any name the claimed blocks flow through —
        # seeded from ``x = ...alloc(...)`` targets and ``share(b)``
        # args, propagated through assignments and for-loop bindings.
        self._flow: set[str] = set()
        self._assigns: list[tuple[set[str], set[str]]] = []
        self._links: list[tuple[set[str], set[str]]] = []
        self._stores: list[set[str]] = []  # nonlocal-store read names
        for stmt in fn.body:
            self._stmt(stmt, in_cleanup=False)

    def _stmt(self, stmt: ast.stmt, in_cleanup: bool):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse:
                self._stmt(s, in_cleanup)
            for s in stmt.finalbody:
                self._stmt(s, True)
            for handler in stmt.handlers:
                for s in handler.body:
                    self._stmt(s, True)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = {n.id for n in ast.walk(stmt.target)
                       if isinstance(n, ast.Name)}
            reads = {n.id for n in ast.walk(stmt.iter)
                     if isinstance(n, ast.Name)}
            self._links.append((targets, reads))
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._stmt(child, in_cleanup)
        self._exprs(stmt, in_cleanup)

    def _exprs(self, stmt: ast.stmt, in_cleanup: bool):
        for node in ast.walk(stmt):
            if isinstance(node, ast.stmt) and node is not stmt:
                continue
            if isinstance(node, ast.Call):
                if _alloc_recv(node) is not None:
                    self.claims.append(node)
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Name):
                                self._flow.add(sub.id)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "free" and in_cleanup):
                    self.free_in_cleanup = True
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self.has_return_value = True
        if isinstance(stmt, ast.Assign):
            claimed = any(_alloc_recv(n) is not None
                          for n in ast.walk(stmt.value)
                          if isinstance(n, ast.Call))
            reads = {n.id for n in ast.walk(stmt.value)
                     if isinstance(n, ast.Name)}
            for t in stmt.targets:
                flat = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for el in flat:
                    if isinstance(el, ast.Name):
                        if claimed:
                            self._flow.add(el.id)
                        else:
                            self._assigns.append(({el.id}, reads))
                    elif isinstance(el, (ast.Attribute, ast.Subscript)):
                        self._stores.append(reads)

    def transferred(self) -> bool:
        """Fixpoint: do the claimed blocks reach a nonlocal store?"""
        flow = set(self._flow)
        for _ in range(10):
            grew = False
            for targets, reads in self._assigns:
                if reads & flow and not targets <= flow:
                    flow |= targets
                    grew = True
            for targets, reads in self._links:
                if targets & flow and not reads <= flow:
                    flow |= reads
                    grew = True
                if reads & flow and not targets <= flow:
                    flow |= targets
                    grew = True
            if not grew:
                break
        return any(reads & flow for reads in self._stores)


def _check(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scan = _FnScan(node)
        if not scan.claims:
            continue
        safe = (scan.free_in_cleanup or scan.has_return_value
                or scan.transferred())
        if safe:
            continue
        symbol = _enclosing(ctx.tree, node)
        first = scan.claims[0]
        recv = _alloc_recv(first)
        yield ("alloc-no-release", first.lineno, symbol,
               f"{recv}.{first.func.attr}() has no free() on an "
               "exception path, no ownership transfer, and no return "
               "— blocks leak if anything below raises (KV-leak "
               "class)")


def _enclosing(tree: ast.AST, target: ast.AST) -> str:
    path: list[str] = []

    def visit(node, stack):
        if node is target:
            path.extend(stack + [target.name])
            return True
        for child in ast.iter_child_nodes(node):
            name = child.name if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.ClassDef)) else None
            if visit(child, stack + [name] if name and child is not
                     target else stack):
                return True
        return False

    visit(tree, [])
    return ".".join(p for p in path if p)


register(Checker(
    name="resource-pairing",
    rules=("alloc-no-release",),
    doc="Allocator alloc/share calls must free on exception paths, "
        "transfer ownership, or return the claim",
    fn=_check,
))
