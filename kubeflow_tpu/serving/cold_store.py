"""Cold content-addressed tier of the fleet KV economy.

The last stop of the KV hierarchy (HBM → host RAM → peer replicas →
HERE): host-tier evictions pack their payload into a PR-9 handoff
envelope (serving/handoff.py — the same JSON-safe blob the
prefill/decode handoff ships over HTTP) and park it in a shared,
byte-bounded, content-addressed store. A fleet-wide miss that finds
its prefix here re-imports the blob through the ordinary
``_install_prefix_payload`` path — exact bytes, never recomputed.

Content addressing keys each blob by ``blake2b(epoch ‖ prefix
tokens)``: the weights epoch is IN the key, so a live weight push
invalidates every pre-swap blob by construction — post-swap lookups
simply hash to keys that do not exist (PR-15's "refuses stale hits"
carried over without a flush pass; LRU pressure reclaims the orphaned
bytes). Deduplication falls out the same way: two replicas demoting
the same (epoch, prefix) write one blob.

In process this is a dict of JSON strings; the ``cold_store_ref`` CRD
knob names an instance (``mem://<name>?bytes=<n>``) so colocated
replicas in one process share one store, and a real object-store
backend can slot behind the same four methods. Thread-safe with its
own leaf lock (callers are every replica's submit probes and
prefix-lock-holding eviction hooks): no method calls out while
holding it.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from dataclasses import dataclass

from kubeflow_tpu.serving import handoff as handoff_mod


def content_key(tokens, version: int) -> str:
    """The blob address for a prefix: ``blake2b(epoch ‖ tokens)``.
    Epoch-first so a weight push moves EVERY prefix to fresh
    addresses — staleness is unreachable, not filtered."""
    h = hashlib.blake2b(digest_size=16)
    h.update(int(version).to_bytes(8, "little", signed=True))
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()


@dataclass
class _ColdBlob:
    key: str
    tokens: tuple[int, ...]
    prefix_len: int
    version: int
    blob: str      # json.dumps of the packed handoff envelope
    nbytes: int
    last_used: int = 0


class ColdKvStore:
    """Bounded-byte LRU of packed handoff envelopes, addressed by
    ``(epoch, prefix)`` content key."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes <= 0:
            raise ValueError("ColdKvStore needs a positive byte budget")
        self.capacity_bytes = int(capacity_bytes)
        self.bytes_in_use = 0
        self._lock = threading.Lock()
        self._blobs: OrderedDict[str, _ColdBlob] = OrderedDict()
        self._clock = 0
        self.puts = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.refused = 0  # puts that could not fit even after eviction

    def __len__(self) -> int:
        with self._lock:
            return len(self._blobs)

    # -- insert --------------------------------------------------------

    def put(self, handoff: dict, *, version: int) -> str | None:
        """Pack ``handoff`` (a decoder export dict: tokens/prefix_len/
        block metadata/payload arrays) and store it under its content
        key. Returns the key, or None when the blob cannot fit.
        Re-putting an existing key refreshes its LRU position without
        re-serializing identical bytes (the key IS the content)."""
        plen = int(handoff["prefix_len"])
        toks = tuple(int(t) for t in handoff["tokens"][:plen])
        key = content_key(toks, version)
        with self._lock:
            old = self._blobs.get(key)
            if old is not None:
                self._tick(old)
                self._blobs.move_to_end(key)
                return key
        # Serialize OUTSIDE the lock: packing base64-encodes the whole
        # payload, and a concurrent probe must not wait on it.
        blob = json.dumps(handoff_mod.pack(handoff))
        nbytes = len(blob)
        with self._lock:
            if key in self._blobs:  # lost a racing identical put — fine
                return key
            if nbytes > self.capacity_bytes:
                self.refused += 1
                return None
            while self.bytes_in_use + nbytes > self.capacity_bytes:
                _, victim = self._blobs.popitem(last=False)
                self.bytes_in_use -= victim.nbytes
                self.evictions += 1
            entry = _ColdBlob(key=key, tokens=toks, prefix_len=plen,
                              version=int(version), blob=blob,
                              nbytes=nbytes)
            self._tick(entry)
            self._blobs[key] = entry
            self.bytes_in_use += nbytes
            self.puts += 1
        return key

    def _tick(self, entry: _ColdBlob) -> None:
        self._clock += 1
        entry.last_used = self._clock

    # -- lookup --------------------------------------------------------

    def peek_depth(self, tokens, version: int) -> int:
        """Deepest stored prefix depth serving ``tokens`` under
        ``version``, without deserializing anything — the crossover
        check's input (import only when the gain clears the
        threshold)."""
        with self._lock:
            return self._best(tokens, version)[1]

    def _best(self, tokens, version: int) -> tuple[_ColdBlob | None, int]:
        """Caller holds the lock. Same interior matching as
        HostKvTier.match: causality makes any shorter depth of a stored
        prefix valid, capped at len(tokens) - 1 so one suffix token
        remains to prefill."""
        cap = len(tokens) - 1
        version = int(version)
        best: tuple[_ColdBlob | None, int] = (None, 0)
        for entry in self._blobs.values():
            if entry.version != version:
                continue
            lim = min(entry.prefix_len, cap)
            if lim <= best[1]:
                continue
            d = 0
            while d < lim and entry.tokens[d] == int(tokens[d]):
                d += 1
            if d > best[1]:
                best = (entry, d)
        return best

    def match(self, tokens, version: int) -> tuple[dict, int] | None:
        """Deepest stored envelope serving a prefix of ``tokens`` under
        weights epoch ``version``: returns ``(handoff, depth)`` with
        the envelope UNPACKED (numpy payload, ready for the importer's
        covering-slice install) — or None. A malformed blob (a future
        backend bitrotting) drops the entry and reports a miss instead
        of handing garbage to a KV pool."""
        with self._lock:
            entry, depth = self._best(tokens, version)
            if entry is None:
                self.misses += 1
                return None
            self._tick(entry)
            self._blobs.move_to_end(entry.key)
            blob = entry.blob
        try:
            handoff = handoff_mod.unpack(json.loads(blob))
        except ValueError:
            with self._lock:
                dead = self._blobs.pop(entry.key, None)
                if dead is not None:
                    self.bytes_in_use -= dead.nbytes
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return handoff, depth

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._blobs),
                "bytes_in_use": self.bytes_in_use,
                "capacity_bytes": self.capacity_bytes,
                "puts": self.puts,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "refused": self.refused,
            }


# -- named in-process instances (the cold_store_ref knob) ---------------

_REGISTRY: dict[str, ColdKvStore] = {}
_REGISTRY_LOCK = threading.Lock()
_DEFAULT_BYTES = 64 << 20


def cold_store_from_ref(ref: str) -> ColdKvStore | None:
    """Resolve a ``cold_store_ref`` CRD/flag value to a store instance.

    ``mem://<name>[?bytes=<n>]`` names a process-global instance —
    colocated replicas (and tests/benches) sharing a ref share the
    store, which is the whole point of a fleet tier. The first
    resolver of a name fixes its capacity. Empty refs resolve to None
    (cold tier off); unknown schemes raise — a typo'd object-store URL
    must fail the rollout, not silently serve without its cold tier.
    """
    ref = str(ref or "").strip()
    if not ref:
        return None
    if not ref.startswith("mem://"):
        raise ValueError(
            f"unsupported cold_store_ref {ref!r} (only mem://<name>"
            f"[?bytes=<n>] is available in-process)")
    name, _, query = ref[len("mem://"):].partition("?")
    if not name:
        raise ValueError("cold_store_ref mem:// needs a store name")
    nbytes = _DEFAULT_BYTES
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k == "bytes" and v:
            nbytes = int(v)
    with _REGISTRY_LOCK:
        store = _REGISTRY.get(name)
        if store is None:
            store = _REGISTRY[name] = ColdKvStore(nbytes)
    return store
