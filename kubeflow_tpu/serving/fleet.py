"""Replicated decoder pool with prefix-affine routing.

The in-process face of the fleet layer (the InferenceService operator
reconciles the same shape out of Deployments + the gateway's
``prefix-affine`` route strategy): N ``ContinuousDecoder`` replicas
behind one ``submit()``, requests placed by rendezvous hash of the
prompt's leading tokens (serving/affinity.py) so each replica's prefix
trie concentrates its own key range's hits.

Placement policy per request:

1. hash the prompt's leading ``affinity_tokens`` into a key and order
   the LIVE replicas by rendezvous score — ``order[0]`` is the affine
   replica;
2. if the affine replica is over the pressure bound (queue depth at or
   past ``pressure``, or KV pool fuller than ``kv_pressure``), spill to
   the least-loaded live replica (deterministic: depth, then rendezvous
   order breaks ties) — locality yields to an actual hotspot, but only
   then;
3. a replica whose scheduler died (submit raises, or an in-flight
   stream fails with the decoder's crash error) is marked dead and
   excluded: its keys remap to the next replica in THEIR rendezvous
   order while every other key stays put.

In-flight streams on a dead replica fail fast with
:class:`ReplicaUnavailableError` (``code=502`` — the status the gateway
relays for a dead upstream), never hang out their timeout.

Host-side composition only: the fleet never touches device state, so it
is exactly as safe as its member decoders.
"""

from __future__ import annotations

import random
import threading

from kubeflow_tpu.serving.affinity import (
    DEFAULT_AFFINITY_TOKENS,
    prefix_affinity_key,
    rendezvous_order,
)


class ReplicaUnavailableError(RuntimeError):
    """A replica died under a request routed to it (HTTP-equivalent 502:
    the backend, not the request, is at fault — clients may retry, and
    the fleet has already excluded the replica)."""

    code = 502

    def __init__(self, replica: str, cause: Exception | None = None):
        super().__init__(
            f"replica {replica!r} is unavailable"
            + (f": {cause}" if cause is not None else ""))
        self.replica = replica
        self.cause = cause


class FleetHandle:
    """Caller-side view of a fleet generation: the member decoder's
    StreamHandle plus the replica it landed on. Replica death surfaces
    as :class:`ReplicaUnavailableError` (and marks the replica dead in
    the fleet) instead of the decoder's raw crash error."""

    def __init__(self, fleet: "DecoderFleet", replica: str, handle):
        self._fleet = fleet
        self.replica = replica
        self._handle = handle

    def _translate(self, err: Exception) -> Exception:
        if self._fleet._is_replica_death(err):
            self._fleet.mark_dead(self.replica, cause=err)
            return ReplicaUnavailableError(self.replica, err)
        return err

    def tokens(self, timeout: float | None = None):
        try:
            yield from self._handle.tokens(timeout)
        except Exception as e:  # noqa: BLE001 — translated and re-raised
            raise self._translate(e) from e

    def result(self, timeout: float | None = None, **kw) -> dict:
        try:
            return self._handle.result(timeout, **kw)
        except Exception as e:  # noqa: BLE001 — translated and re-raised
            raise self._translate(e) from e

    @property
    def ttft_s(self):
        return self._handle.ttft_s


class DecoderFleet:
    """N named decoder replicas behind prefix-affine routing.

    ``replicas`` maps name → a :class:`ContinuousDecoder`-shaped object
    (``submit``/``metrics``/``stop``). ``pressure`` bounds a replica's
    outstanding requests (0 = unbounded, never spill); ``kv_pressure``
    bounds its KV pool fill fraction (0 = ignore). ``router`` is
    "affine" (rendezvous, the default) or "random" (the seeded baseline
    the fleet bench compares against)."""

    def __init__(self, replicas: dict, *,
                 affinity_tokens: int = DEFAULT_AFFINITY_TOKENS,
                 pressure: int = 0, kv_pressure: float = 0.0,
                 router: str = "affine", seed: int = 0):
        if not replicas:
            raise ValueError("DecoderFleet needs at least one replica")
        if router not in ("affine", "random"):
            raise ValueError(f"unknown router {router!r}")
        self._replicas = dict(replicas)
        self.affinity_tokens = int(affinity_tokens)
        self.pressure = int(pressure)
        self.kv_pressure = float(kv_pressure)
        self.router = router
        self._rng = random.Random(seed)
        self._dead: set[str] = set()
        self._lock = threading.Lock()
        self.routed = 0
        self.spilled = 0
        self.remapped = 0  # submits re-routed off a just-dead replica

    # -- membership ----------------------------------------------------

    def members(self) -> list[str]:
        return sorted(self._replicas)

    def live_members(self) -> list[str]:
        with self._lock:
            return sorted(set(self._replicas) - self._dead)

    def mark_dead(self, name: str, cause: Exception | None = None) -> None:
        with self._lock:
            if name in self._replicas:
                self._dead.add(name)

    @staticmethod
    def _is_replica_death(err: Exception) -> bool:
        """The decoder's crash path (_fail_all) propagates WHATEVER
        killed the scheduler loop into every live stream — RuntimeError
        for a graceful stop, the loop's own exception otherwise — and a
        TimeoutError means the replica stopped responding. The only
        error that is the REQUEST's fault is ValueError (admission
        validation, e.g. an over-budget prompt): that must surface to
        the caller, not kill the replica."""
        return not isinstance(err, (ValueError, ReplicaUnavailableError))

    # -- placement -----------------------------------------------------

    def _depth(self, name: str) -> int:
        """Approximate outstanding load (queued + in slots). Reads the
        decoder's counters without its locks — a routing heuristic, not
        an invariant."""
        d = self._replicas[name]
        try:
            return int(getattr(d, "_active_count", 0)
                       + len(getattr(d, "_pending", ())))
        except TypeError:  # pragma: no cover — exotic replica stubs
            return 0

    def _kv_fill(self, name: str) -> float:
        d = self._replicas[name]
        alloc = getattr(d, "_alloc", None)
        if alloc is None or not getattr(alloc, "num_blocks", 0):
            return 0.0
        return alloc.blocks_in_use / alloc.num_blocks

    def _over_pressure(self, name: str) -> bool:
        if self.pressure > 0 and self._depth(name) >= self.pressure:
            return True
        return bool(self.kv_pressure > 0
                    and self._kv_fill(name) >= self.kv_pressure)

    def route(self, tokens) -> str:
        """The replica a prompt should land on (no submission): affine
        pick, pressure spill, dead exclusion."""
        live = self.live_members()
        if not live:
            raise ReplicaUnavailableError("<none>")
        with self._lock:
            self.routed += 1
        if self.router == "random":
            with self._lock:
                return self._rng.choice(live)
        key = prefix_affinity_key(tokens, self.affinity_tokens)
        order = rendezvous_order(key, live)
        primary = order[0]
        if len(order) > 1 and self._over_pressure(primary):
            # Spill: least-loaded live replica; rendezvous order breaks
            # depth ties so the choice is deterministic for a given
            # (key, membership, load) snapshot.
            spill = min(order[1:],
                        key=lambda m: (self._depth(m), order.index(m)))
            if self._depth(spill) < self._depth(primary):
                with self._lock:
                    self.spilled += 1
                return spill
        return primary

    # -- serving surface ----------------------------------------------

    def submit(self, tokens, max_new_tokens: int,
               temperature: float = 0.0, *,
               request_id: str | None = None) -> FleetHandle:
        """Route and submit, re-routing (and marking dead) when the
        chosen replica's scheduler is already gone — a submit never
        fails just because one replica died."""
        while True:
            name = self.route(tokens)
            try:
                handle = self._replicas[name].submit(
                    tokens, max_new_tokens, temperature,
                    request_id=request_id)
            except Exception as e:  # noqa: BLE001 — death check below
                if not self._is_replica_death(e):
                    raise
                self.mark_dead(name, cause=e)
                with self._lock:
                    self.remapped += 1
                if not self.live_members():
                    raise ReplicaUnavailableError(name, e) from e
                continue
            return FleetHandle(self, name, handle)

    def generate(self, tokens, max_new_tokens: int,
                 temperature: float = 0.0,
                 timeout: float | None = None) -> dict:
        return self.submit(tokens, max_new_tokens, temperature).result(
            timeout)

    def metrics(self) -> dict:
        """Per-replica decoder metrics plus fleet aggregates (the bench
        and the autoscaler read the same names the single-decoder
        metrics() exposes, summed over live replicas)."""
        per: dict[str, dict] = {}
        for name in self.members():
            if name in self._dead:
                continue
            per[name] = self._replicas[name].metrics()
        agg_keys = ("tokens_emitted", "requests_admitted", "prefix_hits",
                    "prefix_misses", "kv_blocks_in_use", "in_flight",
                    "queued")
        agg = {k: sum(m.get(k, 0) for m in per.values()) for k in agg_keys}
        agg.update(replicas=per, live=self.live_members(),
                   dead=sorted(self._dead), routed=self.routed,
                   spilled=self.spilled, remapped=self.remapped)
        return agg

    def stop(self) -> None:
        for name, d in self._replicas.items():
            try:
                d.stop()
            except Exception:  # pragma: no cover — best-effort teardown
                pass
