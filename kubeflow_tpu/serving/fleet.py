"""Replicated decoder pool with prefix-affine routing.

The in-process face of the fleet layer (the InferenceService operator
reconciles the same shape out of Deployments + the gateway's
``prefix-affine`` route strategy): N ``ContinuousDecoder`` replicas
behind one ``submit()``, requests placed by rendezvous hash of the
prompt's leading tokens (serving/affinity.py) so each replica's prefix
trie concentrates its own key range's hits.

Placement policy per request:

1. hash the prompt's leading ``affinity_tokens`` into a key and order
   the LIVE replicas by rendezvous score — ``order[0]`` is the affine
   replica;
2. if the affine replica is over the pressure bound (queue depth at or
   past ``pressure``, or KV pool fuller than ``kv_pressure``), spill to
   the least-loaded live replica (deterministic: depth, then rendezvous
   order breaks ties) — locality yields to an actual hotspot, but only
   then;
3. a replica whose scheduler died (submit raises, or an in-flight
   stream fails with the decoder's crash error) is marked dead and
   excluded: its keys remap to the next replica in THEIR rendezvous
   order while every other key stays put.

In-flight streams on a dead replica fail fast with
:class:`ReplicaUnavailableError` (``code=502`` — the status the gateway
relays for a dead upstream), never hang out their timeout.

Host-side composition only: the fleet never touches device state, so it
is exactly as safe as its member decoders.
"""

from __future__ import annotations

import random
import threading

from kubeflow_tpu.serving.affinity import (
    DEFAULT_AFFINITY_TOKENS,
    prefix_affinity_key,
    rendezvous_order,
)


class ReplicaUnavailableError(RuntimeError):
    """A replica died under a request routed to it (HTTP-equivalent 502:
    the backend, not the request, is at fault — clients may retry, and
    the fleet has already excluded the replica)."""

    code = 502

    def __init__(self, replica: str, cause: Exception | None = None):
        super().__init__(
            f"replica {replica!r} is unavailable"
            + (f": {cause}" if cause is not None else ""))
        self.replica = replica
        self.cause = cause


class FleetHandle:
    """Caller-side view of a fleet generation: the member decoder's
    StreamHandle plus the replica it landed on. Replica death surfaces
    as :class:`ReplicaUnavailableError` (and marks the replica dead in
    the fleet) instead of the decoder's raw crash error."""

    def __init__(self, fleet: "DecoderFleet", replica: str, handle):
        self._fleet = fleet
        self.replica = replica
        self._handle = handle

    def _translate(self, err: Exception) -> Exception:
        if self._fleet._is_replica_death(err):
            self._fleet.mark_dead(self.replica, cause=err)
            return ReplicaUnavailableError(self.replica, err)
        return err

    def tokens(self, timeout: float | None = None):
        try:
            yield from self._handle.tokens(timeout)
        except Exception as e:  # noqa: BLE001 — translated and re-raised
            raise self._translate(e) from e

    def result(self, timeout: float | None = None, **kw) -> dict:
        try:
            return self._handle.result(timeout, **kw)
        except Exception as e:  # noqa: BLE001 — translated and re-raised
            raise self._translate(e) from e

    @property
    def ttft_s(self):
        return self._handle.ttft_s


class DecoderFleet:
    """N named decoder replicas behind prefix-affine routing.

    ``replicas`` maps name → a :class:`ContinuousDecoder`-shaped object
    (``submit``/``metrics``/``stop``). ``pressure`` bounds a replica's
    outstanding requests (0 = unbounded, never spill); ``kv_pressure``
    bounds its KV pool fill fraction (0 = ignore). ``router`` is
    "affine" (rendezvous, the default) or "random" (the seeded baseline
    the fleet bench compares against).

    **Disaggregated mode**: replicas carrying ``role == "prefill"``
    (the decoder's own attribute — the same knob the CRD's role
    overrides set) form a prefill pool that runs prompt admission only.
    A submit then becomes the two-hop relay: affine-pick a prefill
    replica and ``export_prompt`` the prompt's KV there, place the
    decode leg on the least-KV-loaded decode replica, ``import_prompt``
    the blocks, and submit the full prompt — which rides the ordinary
    prefix-hit admission against the imported entry, so long prompts
    never stall the decode pool's token cadence behind compute-bound
    prefill dispatches. A failed import (cache full, pool pressure)
    degrades to a plain submit — the decode replica prefills the prompt
    itself: slower, never wrong. A prefill replica dying mid-handoff
    fails that submit fast with the 502-coded error (the fleet excludes
    it; only its affinity keys remap); with the whole prefill pool dead
    the fleet degrades to colocated submits on the decode pool."""

    def __init__(self, replicas: dict, *,
                 affinity_tokens: int = DEFAULT_AFFINITY_TOKENS,
                 pressure: int = 0, kv_pressure: float = 0.0,
                 router: str = "affine", seed: int = 0,
                 weights_max_lag: int = 0):
        if not replicas:
            raise ValueError("DecoderFleet needs at least one replica")
        if router not in ("affine", "random"):
            raise ValueError(f"unknown router {router!r}")
        self._replicas = dict(replicas)
        self._roles = {
            name: getattr(d, "role", "") or ""
            for name, d in self._replicas.items()
        }
        if any(r == "prefill" for r in self._roles.values()) and not any(
                r != "prefill" for r in self._roles.values()):
            raise ValueError(
                "a disaggregated fleet needs at least one decode replica")
        self.affinity_tokens = int(affinity_tokens)
        self.pressure = int(pressure)
        self.kv_pressure = float(kv_pressure)
        self.router = router
        self._rng = random.Random(seed)
        self._dead: set[str] = set()
        self._lock = threading.Lock()
        self.routed = 0
        self.spilled = 0
        self.remapped = 0  # submits re-routed off a just-dead replica
        self.replicas_added = 0  # newborns joined via add_replica
        self.handoffs = 0           # prefill→decode KV relays completed
        self.handoff_fallbacks = 0  # degraded to a plain decode submit
        self.handoff_skipped = 0    # prompts too short to register
        # Live weight streaming: highest weights epoch any replica has
        # installed, per-replica installed epochs, and the skew bound.
        # A replica lagging the fleet by more than ``weights_max_lag``
        # pushes (0 = unbounded) is excluded from ROUTING until a later
        # push lands on it — stragglers converge on the next push, and
        # no request is ever served by weights older than the bound.
        self.weights_max_lag = int(weights_max_lag)
        self._weights_latest = 0
        self._weights_installed: dict[str, int] = {}
        self.weight_pushes = 0          # broadcast_weights calls
        self.weight_push_failures = 0   # per-replica push failures
        # Fleet KV economy: adopt the members' shared prefix directory
        # and cold store (every economy-enabled replica is constructed
        # with the SAME instances — the directory is only useful
        # fleet-wide), and close the loop by installing the in-process
        # peer-fetch path on any replica that has a directory but no
        # transport yet: a replica's submit-time probe then pulls the
        # holder's exported prefix through the PR-9 envelope codec,
        # exactly the bytes the HTTP ``:kv`` endpoint would ship.
        self.kv_directory = next(
            (getattr(d, "kv_directory", None)
             for d in self._replicas.values()
             if getattr(d, "kv_directory", None) is not None), None)
        self.cold_store = next(
            (getattr(d, "cold_store", None)
             for d in self._replicas.values()
             if getattr(d, "cold_store", None) is not None), None)
        for d in self._replicas.values():
            if (getattr(d, "kv_directory", None) is not None
                    and getattr(d, "_peer_fetch", None) is None):
                d._peer_fetch = self._peer_fetch

    # -- membership ----------------------------------------------------

    def members(self) -> list[str]:
        return sorted(self._replicas)

    def live_members(self) -> list[str]:
        with self._lock:
            return sorted(set(self._replicas) - self._dead)

    def role_of(self, name: str) -> str:
        return self._roles.get(name, "")

    def _warming(self, name: str) -> bool:
        return bool(getattr(self._replicas.get(name), "warming", False))

    def add_replica(self, name: str, decoder, *,
                    warming: bool = True) -> None:
        """Join a newborn replica to a RUNNING fleet (the flash-crowd
        scale-up path; construction-time membership stays the common
        case). The newborn is wired into the fleet's KV economy (shared
        directory adopted, the in-process peer-fetch transport
        installed) and its installed weights epoch is recorded from the
        decoder's own ``weights_version`` — a peer-born decoder stamped
        its donor's epoch at construction, so a concurrent rollout's
        lag accounting sees it as current, not lagging from epoch 0.

        ``warming=True`` (default) admits it via least-loaded spill
        only — no affine key share — until :meth:`mark_warm`; pass
        False for a replica already warmed (e.g. compile-cache birth
        where the dispatch set deserialized).

        Membership mutation is CONTROL-PLANE and single-writer (the
        operator's reconcile loop, a test, or the bench harness) —
        hot-path readers stay lock-free because the membership dicts
        are never mutated in place: a join builds fresh dicts and
        publishes them by atomic reference swap, so a concurrent
        route sees either the old complete snapshot or the new one,
        never a dict growing under iteration."""
        if name in self._replicas:
            raise ValueError(f"replica {name!r} already in the fleet")
        decoder.warming = bool(warming)
        role = getattr(decoder, "role", "") or ""
        with self._lock:
            self.replicas_added += 1
            ver = int(getattr(decoder, "weights_version", 0) or 0)
            if ver:
                self._weights_installed[name] = ver
        self._replicas = {**self._replicas, name: decoder}
        self._roles = {**self._roles, name: role}
        if self.kv_directory is None:
            self.kv_directory = getattr(decoder, "kv_directory", None)
        if self.cold_store is None:
            self.cold_store = getattr(decoder, "cold_store", None)
        if (getattr(decoder, "kv_directory", None) is not None
                and getattr(decoder, "_peer_fetch", None) is None):
            decoder._peer_fetch = self._peer_fetch

    def mark_warm(self, name: str) -> None:
        """Flip a newborn into full affine membership: the next route
        recomputes rendezvous order with it eligible, so exactly the
        keys that hash to it move — every other key stays put."""
        d = self._replicas.get(name)
        if d is not None:
            d.warming = False

    def donor_for(self, name: str = "") -> str | None:
        """A live, warm, non-lagging replica to pull birth weights from
        (the in-process analogue of the operator rendering lower-
        indexed siblings into ``--weight-peers``). ``name`` excludes
        the newborn itself. None when no viable donor exists — the
        caller falls back to checkpoint birth."""
        live = self._fresh(self.live_members())
        for m in live:
            if m != name and not self._warming(m):
                return m
        return None

    @property
    def disaggregated(self) -> bool:
        return any(r == "prefill" for r in self._roles.values())

    def _live_pool(self, prefill: bool) -> list[str]:
        """Live members of one role pool. Decode pool = every non-
        prefill replica (colocated replicas can take decode legs)."""
        return [m for m in self.live_members()
                if (self._roles[m] == "prefill") == prefill]

    def _fresh(self, live: list[str]) -> list[str]:
        """Drop replicas lagging the fleet's weights epoch by more than
        ``weights_max_lag`` pushes. At least one live replica always
        carries the latest epoch (it defined it), so the fallback to
        the raw list only fires when every fresh replica has since
        died — availability then beats freshness."""
        if self.weights_max_lag <= 0:
            return live
        with self._lock:
            latest = self._weights_latest
            if latest <= 0:
                return live
            fresh = [m for m in live
                     if latest - self._weights_installed.get(m, 0)
                     <= self.weights_max_lag]
        return fresh or live

    def mark_dead(self, name: str, cause: Exception | None = None) -> None:
        with self._lock:
            if name not in self._replicas:
                return
            self._dead.add(name)
        # Sweep the dead replica's directory hints OUTSIDE the fleet
        # lock (the directory carries its own leaf lock): its advertised
        # KV died with it, and a requester probing a stale hint would
        # burn a failed fetch per submit until withdrawal. Cold-tier
        # hints survive — the cold store outlives any one replica.
        if self.kv_directory is not None:
            self.kv_directory.drop_holder(name)

    def _peer_fetch(self, holder: str, tokens, version: int):
        """In-process peer KV pull (the transport the remote fleet
        replaces with the ``:kv`` HTTP endpoint): export the deepest
        cached prefix on ``holder`` and ship it as a packed handoff
        envelope — the requester unpacks, validates, and refuses it
        exactly as it would a remote one. Returns None on any miss or
        holder death; the caller withdraws the hint and falls through
        (cold tier, then prefill) — a dead holder costs one probe,
        never a hang."""
        from kubeflow_tpu.serving import handoff as handoff_mod

        with self._lock:
            d = self._replicas.get(holder)
            if d is None or holder in self._dead:
                return None
        try:
            h = d.export_prefix(list(tokens))
        except KeyError:
            return None  # hint was stale: holder evicted it meanwhile
        except Exception as e:  # noqa: BLE001 — death check below
            if self._is_replica_death(e):
                self.mark_dead(holder, cause=e)
            return None
        ver = h.pop("weights_version", 0)
        return {"envelope": handoff_mod.pack(h), "weights_version": ver}

    @staticmethod
    def _is_replica_death(err: Exception) -> bool:
        """The decoder's crash path (_fail_all) propagates WHATEVER
        killed the scheduler loop into every live stream — RuntimeError
        for a graceful stop, the loop's own exception otherwise — and a
        TimeoutError means the replica stopped responding. The errors
        that are the REQUEST's fault — ValueError (admission
        validation, e.g. an over-budget prompt) and QosRejected (the
        tenant is over rate; DeadlineExceeded is a TimeoutError but
        carries its own type) — must surface to the caller, not kill
        the replica."""
        from kubeflow_tpu.serving.qos import DeadlineExceeded, QosRejected

        return not isinstance(err, (ValueError, ReplicaUnavailableError,
                                    QosRejected, DeadlineExceeded))

    # -- placement -----------------------------------------------------

    def _depth(self, name: str) -> int:
        """Approximate outstanding load (queued + in slots). Reads the
        decoder's counters without its locks — a routing heuristic, not
        an invariant."""
        d = self._replicas[name]
        try:
            return int(getattr(d, "_active_count", 0)
                       + len(getattr(d, "_pending", ())))
        except TypeError:  # pragma: no cover — exotic replica stubs
            return 0

    def _kv_fill(self, name: str) -> float:
        d = self._replicas[name]
        alloc = getattr(d, "_alloc", None)
        if alloc is None or not getattr(alloc, "num_blocks", 0):
            return 0.0
        return alloc.blocks_in_use / alloc.num_blocks

    def _over_pressure(self, name: str) -> bool:
        if self.pressure > 0 and self._depth(name) >= self.pressure:
            return True
        return bool(self.kv_pressure > 0
                    and self._kv_fill(name) >= self.kv_pressure)

    def _route_among(self, tokens, live: list[str]) -> str:
        live = self._fresh(live)
        if not live:
            raise ReplicaUnavailableError("<none>")
        with self._lock:
            self.routed += 1
        if self.router == "random":
            with self._lock:
                return self._rng.choice(live)
        key = prefix_affinity_key(tokens, self.affinity_tokens)
        order = rendezvous_order(key, live)
        # Ramped admission: a WARMING newborn takes no affine share —
        # its keys stay on the established replicas until it reports
        # warm (then they rebalance by plain rendezvous order on the
        # next route) — but it stays in the spill pool below, so a
        # genuine hotspot can overflow onto it immediately. All-warming
        # degenerates to plain rendezvous: availability beats ramp.
        primary = next((m for m in order if not self._warming(m)),
                       order[0])
        if len(order) > 1 and self._over_pressure(primary):
            # Spill: least-loaded live replica; rendezvous order breaks
            # depth ties so the choice is deterministic for a given
            # (key, membership, load) snapshot.
            spill = min((m for m in order if m != primary),
                        key=lambda m: (self._depth(m), order.index(m)))
            if self._depth(spill) < self._depth(primary):
                with self._lock:
                    self.spilled += 1
                return spill
        return primary

    def route(self, tokens) -> str:
        """The replica a prompt should land on (no submission): affine
        pick, pressure spill, dead exclusion. In a disaggregated fleet
        this is the PREFILL hop — the affinity-bearing placement (the
        decode leg is load-placed, see :meth:`route_decode`)."""
        if self.disaggregated:
            return self.route_prefill(tokens)
        return self._route_among(tokens, self.live_members())

    def route_prefill(self, tokens) -> str:
        """Affine pick over the live prefill pool (disaggregated
        fleets): shared prefixes keep concentrating on one trie, whose
        replica now does nothing but prefill them."""
        return self._route_among(tokens, self._live_pool(prefill=True))

    def route_decode(self) -> str:
        """The decode leg's placement: least-KV-loaded live decode
        replica (real-byte fill is what binds a decode pool), depth then
        name breaking ties deterministically."""
        live = self._fresh(self._live_pool(prefill=False))
        if not live:
            raise ReplicaUnavailableError("<none>")
        return min(live, key=lambda m: (self._kv_fill(m),
                                        self._depth(m), m))

    # -- serving surface ----------------------------------------------

    def _handoff_viable(self, tokens) -> bool:
        """A handoff is worth attempting only when some live decode
        replica could register it — the exported prefix (prompt minus
        one token) must clear the decode trie's ``min_len``. Short
        long-decode prompts skip the relay entirely instead of paying
        an export that the import would refuse."""
        n = len(list(tokens)) - 1
        for m in self._live_pool(prefill=False):
            cache = getattr(self._replicas[m], "prefix_cache", None)
            if cache is not None and n >= cache.min_len:
                return True
        return False

    def _prefill_handoff(self, tokens):
        """Hop 1 of a disaggregated submit: export the prompt's KV on
        the affine prefill replica. Returns the handoff dict, or None
        when the fleet must degrade to a plain decode-side prefill
        (prefill pool entirely dead, or the export was refused).
        A replica dying UNDER the export fails this submit fast with
        the 502-coded error — the in-flight handoff is lost, the
        replica is excluded, and only its keys remap on the next
        submit."""
        if not self._live_pool(prefill=True):
            with self._lock:
                self.handoff_fallbacks += 1
            return None
        name = self.route_prefill(tokens)
        try:
            return self._replicas[name].export_prompt(tokens)
        except Exception as e:  # noqa: BLE001 — death check below
            if not self._is_replica_death(e):
                # The request's fault (e.g. a 1-token prompt): prefill
                # it on the decode side instead of failing the submit.
                with self._lock:
                    self.handoff_fallbacks += 1
                return None
            self.mark_dead(name, cause=e)
            raise ReplicaUnavailableError(name, e) from e

    def submit(self, tokens, max_new_tokens: int,
               temperature: float = 0.0, *,
               request_id: str | None = None, tenant: str = "",
               priority: int | None = None,
               deadline_ms: float = 0.0) -> FleetHandle:
        """Route and submit, re-routing (and marking dead) when the
        chosen replica's scheduler is already gone — a submit never
        fails just because one replica died. Disaggregated fleets run
        the two-hop relay first: prefill-pool export, decode-pool
        import, then the decode submit below (which prefix-hits the
        imported blocks). ``tenant``/``priority``/``deadline_ms``
        thread through to the replica's QoS admission (a QosRejected
        bubbles to the caller — an over-rate tenant is not a replica
        death)."""
        # QoS kwargs forwarded only when set, so duck-typed replicas
        # (test stubs, wrappers) without the QoS surface keep working
        # for tenant-less traffic.
        qos_kw = {}
        if tenant:
            qos_kw["tenant"] = tenant
        if priority is not None:
            qos_kw["priority"] = priority
        if deadline_ms:
            qos_kw["deadline_ms"] = deadline_ms
        handoff = None
        if self.disaggregated:
            if self._handoff_viable(tokens):
                handoff = self._prefill_handoff(tokens)
            else:
                with self._lock:
                    self.handoff_skipped += 1
        while True:
            name = (self.route_decode() if self.disaggregated
                    else self.route(tokens))
            try:
                if handoff is not None:
                    if self._replicas[name].import_prompt(handoff):
                        with self._lock:
                            self.handoffs += 1
                    else:
                        with self._lock:
                            self.handoff_fallbacks += 1
                handle = self._replicas[name].submit(
                    tokens, max_new_tokens, temperature,
                    request_id=request_id, **qos_kw)
            except Exception as e:  # noqa: BLE001 — death check below
                if not self._is_replica_death(e):
                    raise
                self.mark_dead(name, cause=e)
                with self._lock:
                    self.remapped += 1
                if not self.live_members():
                    raise ReplicaUnavailableError(name, e) from e
                continue
            return FleetHandle(self, name, handle)

    def generate(self, tokens, max_new_tokens: int,
                 temperature: float = 0.0,
                 timeout: float | None = None) -> dict:
        return self.submit(tokens, max_new_tokens, temperature).result(
            timeout)

    # -- live weight streaming ----------------------------------------

    # Fan-out bound: a fleet-wide push at scale must not spawn a thread
    # per replica — 16 concurrent host→device copies saturate the host
    # NIC/PCIe long before 100 threads would help.
    BROADCAST_MAX_WORKERS = 16

    def broadcast_weights(self, params, *, version: int | None = None,
                          draft_params=None,
                          members: list[str] | None = None) -> dict:
        """Fan a weight push out to every live replica CONCURRENTLY
        (each replica's ``update_weights`` double-buffers and swaps
        independently; one slow host→device copy must not serialize
        the fleet behind it). A replica dying mid-push is marked dead
        and excluded — the broadcast completes on the survivors, and a
        straggler that comes back converges on the NEXT push (per-
        replica installed epochs + ``weights_max_lag`` keep it out of
        routing meanwhile). A push failure that is the PUSH's fault
        (shape mismatch) is reported per replica, never kills one.

        ``members`` targets a named subset (the canary path: a rollout
        pushes the candidate epoch into a few replicas while the rest
        keep serving the incumbent); unknown names are reported in
        ``failed`` rather than raising, so a rollout racing a replica
        removal degrades to evidence instead of an exception. A subset
        push does NOT advance the fleet's notion of "every live member
        should hold latest": ``_weights_latest`` still tracks the max
        installed epoch, and members outside the subset show up in
        ``lagging`` — exactly what the rollout controller reads to
        know the canary diverged on purpose.

        Returns ``{"version", "installed": {replica: epoch},
        "failed": {replica: error}, "lagging": [replica, ...]}``."""
        from concurrent.futures import ThreadPoolExecutor

        with self._lock:
            if version is not None:
                target = int(version)
            else:
                # CLAIM the epoch under the lock, not just read it: two
                # racing auto-increment broadcasts (a rollback push vs
                # a learner's live push) that both computed latest+1
                # would install the SAME epoch with different params —
                # per-replica update_weights would then no-op whichever
                # push arrived second, leaving the fleet epoch-uniform
                # but weight-torn and undetectably so. Claiming makes
                # the second racer pick a strictly higher epoch, so the
                # race resolves by monotonicity like every other skew.
                target = self._weights_latest + 1
                self._weights_latest = target
        # Attempt EVERY member, dead included: a replica that died (or
        # was preempted) and came back converges on the next push — a
        # landed install on a replica whose scheduler is alive revives
        # it into routing.
        names = self.members()
        unknown: dict[str, str] = {}
        if members is not None:
            known = set(names)
            unknown = {m: "unknown fleet member" for m in members
                       if m not in known}
            names = [n for n in names if n in set(members)]

        def push(name):
            try:
                return name, self._replicas[name].update_weights(
                    params, version=target,
                    draft_params=draft_params), None
            except Exception as e:  # noqa: BLE001 — death check below
                return name, None, e

        installed: dict[str, int] = {}
        failed: dict[str, str] = dict(unknown)
        if names:
            workers = min(len(names), self.BROADCAST_MAX_WORKERS)
            with ThreadPoolExecutor(max_workers=workers) as pool:
                outcomes = list(pool.map(push, names))
            for name, ver, err in outcomes:
                if err is None:
                    installed[name] = ver
                elif self._is_replica_death(err):
                    self.mark_dead(name, cause=err)
                    failed[name] = str(err)
                else:
                    failed[name] = str(err)
        with self._lock:
            self.weight_pushes += 1
            self.weight_push_failures += len(failed)
            for name, ver in installed.items():
                self._weights_installed[name] = max(
                    ver, self._weights_installed.get(name, 0))
                # Revive a previously-dead replica the push landed on —
                # unless its scheduler loop is known-stopped (a stopped
                # decoder still swaps params fine; routing to it would
                # just re-kill it).
                if not getattr(self._replicas[name], "_stopped", False):
                    self._dead.discard(name)
            if installed:
                self._weights_latest = max(self._weights_latest,
                                           max(installed.values()))
            latest = self._weights_latest
            lagging = sorted(
                m for m in set(self._replicas) - self._dead
                if latest - self._weights_installed.get(m, 0) > 0)
        return {"version": target, "installed": installed,
                "failed": failed, "lagging": lagging}

    def weights_versions(self) -> dict:
        """Per-replica installed weights epoch plus the fleet's latest
        (dashboards and the RL learner's skew check read this)."""
        with self._lock:
            return {"latest": self._weights_latest,
                    "installed": dict(self._weights_installed),
                    "max_lag": self.weights_max_lag}

    def metrics(self) -> dict:
        """Per-replica decoder metrics plus fleet aggregates (the bench
        and the autoscaler read the same names the single-decoder
        metrics() exposes, summed over live replicas)."""
        # Snapshot the mutable fleet state under its lock: mark_dead()
        # runs on caller threads mid-submit, and iterating the live set
        # while it grows is a torn read at best, a RuntimeError at
        # worst (surfaced by tpu-lint lock-inconsistent-guard).
        with self._lock:
            dead = sorted(self._dead)
            counters = {
                "routed": self.routed, "spilled": self.spilled,
                "replicas_added": self.replicas_added,
                "remapped": self.remapped, "handoffs": self.handoffs,
                "handoff_fallbacks": self.handoff_fallbacks,
                "handoff_skipped": self.handoff_skipped,
                "weight_pushes": self.weight_pushes,
                "weight_push_failures": self.weight_push_failures,
                "weights_latest": self._weights_latest,
                "weights_installed": dict(self._weights_installed),
            }
        per: dict[str, dict] = {}
        for name in self.members():
            if name in dead:
                continue
            per[name] = self._replicas[name].metrics()
        agg_keys = ("tokens_emitted", "requests_admitted", "prefix_hits",
                    "prefix_misses", "kv_blocks_in_use", "in_flight",
                    "queued", "prefill_chunks", "prompt_rejected_too_long",
                    "prefill_tokens", "kv_peer_hits", "kv_peer_misses",
                    "kv_peer_import_bytes", "kv_peer_fetch_failures",
                    "kv_cold_hits", "kv_cold_demotions",
                    "kv_import_stale_refused")
        agg = {k: sum(m.get(k, 0) for m in per.values()) for k in agg_keys}
        if self.kv_directory is not None:
            agg["kv_directory"] = self.kv_directory.stats()
        if self.cold_store is not None:
            agg["kv_cold_store"] = self.cold_store.stats()
        agg.update(replicas=per, live=sorted(per),
                   warming=sorted(m for m in per if self._warming(m)),
                   replicas_added=counters["replicas_added"],
                   dead=dead, routed=counters["routed"],
                   spilled=counters["spilled"],
                   remapped=counters["remapped"],
                   weight_pushes=counters["weight_pushes"],
                   weight_push_failures=counters["weight_push_failures"],
                   weights_latest=counters["weights_latest"],
                   weights_installed=counters["weights_installed"])
        if self.disaggregated:
            agg.update(
                roles=dict(self._roles),
                prefill_pool=self._live_pool(prefill=True),
                decode_pool=self._live_pool(prefill=False),
                handoffs=counters["handoffs"],
                handoff_fallbacks=counters["handoff_fallbacks"],
                handoff_skipped=counters["handoff_skipped"],
            )
        return agg

    def stop(self) -> None:
        for name, d in self._replicas.items():
            try:
                d.stop()
            except Exception:  # pragma: no cover — best-effort teardown
                pass
