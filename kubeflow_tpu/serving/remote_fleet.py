"""HTTP actor fleet for the RL learner — the cross-pod twin of
:class:`~kubeflow_tpu.serving.fleet.DecoderFleet`.

Inside one RLJob, learner and actors are separate gangs: the learner
pod reaches each actor's model server over HTTP (pod DNS injected by
the RLJob operator). This module is the minimal client surface the
learner loop (:mod:`kubeflow_tpu.train.rl`) needs from a fleet:

- ``generate`` — one rollout over ``:predict`` (round-robin with dead-
  target exclusion; a dead actor costs throughput, never the run);
- ``broadcast_weights`` — the chunked weight push
  (:func:`kubeflow_tpu.serving.weights.push_weights`) fanned out
  CONCURRENTLY at each actor's ``:weights`` endpoint, straggler-
  tolerant with the same ``max_lag`` routing exclusion as the
  in-process fleet;
- ``metrics``/``stop`` — enough bookkeeping for the result dict.

Weight bytes travel learner→actor directly, never through the gateway.
"""

from __future__ import annotations

import json
import threading
from http.client import HTTPConnection

from kubeflow_tpu.serving.affinity import (
    DEFAULT_AFFINITY_TOKENS,
    prefix_affinity_key,
)
from kubeflow_tpu.serving.weights import DEFAULT_CHUNK_BYTES, push_weights


class RemoteActorFleet:
    """Round-robin rollout client + weight broadcaster over HTTP
    model-server targets (``host:port`` each).

    ``kv_directory`` (optional, a
    :class:`~kubeflow_tpu.serving.kv_directory.KvDirectory`) makes the
    round-robin KV-economy aware: a rollout whose prompt prefix is
    advertised by a live target lands there (the holder's trie/host
    tier already carries the bytes — RL rollouts share the task prompt,
    so this is the common case), successful rollouts publish their
    target as a holder, and a target marked dead has its hints swept —
    the same directory object the in-process fleet and the gateway
    maintain, so all three planes agree on who holds what."""

    def __init__(self, targets: list[str], model: str, *,
                 weights_max_lag: int = 0,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 timeout: float = 600.0,
                 kv_directory=None,
                 affinity_tokens: int = DEFAULT_AFFINITY_TOKENS):
        if not targets:
            raise ValueError("RemoteActorFleet needs at least one target")
        self.targets = list(targets)
        self.model = model
        self.weights_max_lag = int(weights_max_lag)
        self.chunk_bytes = int(chunk_bytes)
        self.timeout = float(timeout)
        self.kv_directory = kv_directory
        self.affinity_tokens = int(affinity_tokens)
        self._lock = threading.Lock()
        self._rr = 0
        self._dead: set[str] = set()
        self._weights_latest = 0
        self._weights_installed: dict[str, int] = {}
        self.weight_pushes = 0
        self.weight_push_failures = 0
        self.rollouts = 0
        self.directory_routed = 0  # rollouts placed on an advertised holder

    # -- routing -------------------------------------------------------

    def _live(self) -> list[str]:
        with self._lock:
            live = [t for t in self.targets if t not in self._dead]
            latest = self._weights_latest
            if self.weights_max_lag > 0 and latest > 0:
                fresh = [t for t in live
                         if latest - self._weights_installed.get(t, 0)
                         <= self.weights_max_lag]
                live = fresh or live
        return live

    def _pick(self, key: str | None = None) -> str:
        live = self._live()
        if not live:
            raise RuntimeError("every actor target is dead")
        if key is not None and self.kv_directory is not None:
            holders = [h for h in self.kv_directory.holders(key)
                       if h in live]
            if holders:
                with self._lock:
                    self.directory_routed += 1
                    return holders[0]  # deepest advertised prefix
        with self._lock:
            self._rr += 1
            return live[self._rr % len(live)]

    def add_target(self, target: str) -> None:
        """Join a newborn actor address to the rotation (the
        flash-crowd scale-up path — the operator scaled the pool and
        the new pod's DNS just resolved). Its installed weights epoch
        converges on the next broadcast; until then the max-lag
        exclusion treats it exactly like any straggler.

        Control-plane, single-writer: the rotation list is published
        by atomic reference swap (never mutated in place), so the
        lock-free pick path sees a complete snapshot either way."""
        with self._lock:
            self._dead.discard(target)
        if target not in self.targets:
            self.targets = [*self.targets, target]

    def donors(self, exclude: str = "") -> list[str]:
        """Live, non-lagging targets ordered for a newborn's
        ``--weight-peers`` fallback chain (each is a valid source for
        :func:`kubeflow_tpu.serving.weights.pull_weights`); ``exclude``
        drops the newborn's own address."""
        return [t for t in self._live() if t != exclude]

    def _mark_dead(self, target: str) -> None:
        with self._lock:
            self._dead.add(target)
        if self.kv_directory is not None:
            # The target's advertised KV died with its process; stale
            # hints would keep steering rollouts at a dead pod.
            self.kv_directory.drop_holder(target)

    # -- rollouts ------------------------------------------------------

    def generate(self, tokens, max_new_tokens: int,
                 temperature: float = 0.0,
                 timeout: float | None = None) -> dict:
        body = json.dumps({"instances": [{
            "tokens": [int(t) for t in tokens],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
        }]}).encode()
        key = (prefix_affinity_key(tokens, self.affinity_tokens)
               if self.kv_directory is not None else None)
        last_err: Exception | None = None
        for _ in range(len(self.targets)):
            target = self._pick(key)
            host, _, port_s = target.partition(":")
            try:
                conn = HTTPConnection(host, int(port_s or 80),
                                      timeout=timeout or self.timeout)
                try:
                    conn.request(
                        "POST", f"/v1/models/{self.model}:predict",
                        body=body,
                        headers={"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    payload = json.loads(resp.read() or b"{}")
                finally:
                    conn.close()
                if resp.status != 200:
                    raise OSError(
                        f"{target} answered {resp.status}: "
                        f"{payload.get('error', '')}")
                pred = payload["predictions"][0]
                with self._lock:
                    self.rollouts += 1
                if key is not None:
                    # The served prompt's prefix now lives in the
                    # target's trie (its decoder pools finishing
                    # prompts) — advertise it so the next rollout
                    # sharing the prompt lands on the warm replica.
                    self.kv_directory.publish(
                        key, target,
                        prefix_len=max(len(list(tokens)) - 1, 0),
                        tier="route")
                return {"tokens": pred.get("tokens", []),
                        "finish_reason": pred.get("finish_reason", "")}
            except (OSError, ValueError, KeyError, IndexError) as e:
                last_err = e
                self._mark_dead(target)
        raise RuntimeError(
            f"every actor target failed; last error: {last_err}")

    def fetch_kv(self, target: str, tokens, version: int = 0):
        """Peer KV pull over HTTP — the cross-pod transport for a
        decoder's ``peer_fetch`` hook, shaped to its contract: POST the
        prompt at the holder's ``:kv`` endpoint and return
        ``{"envelope": <packed handoff>, "weights_version": v}``, or
        None on any failure (404 = the holder no longer caches the
        prefix; the requester withdraws the hint and falls through).
        ``version`` rides along so the holder can refuse the export
        outright when its own epoch already moved past the
        requester's."""
        host, _, port_s = str(target).partition(":")
        body = json.dumps({"tokens": [int(t) for t in tokens],
                           "weights_version": int(version)}).encode()
        try:
            conn = HTTPConnection(host, int(port_s or 80),
                                  timeout=self.timeout)
            try:
                conn.request("POST", f"/v1/models/{self.model}:kv",
                             body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError):
            self._mark_dead(target)
            return None
        if resp.status != 200 or "envelope" not in payload:
            return None
        return {"envelope": payload["envelope"],
                "weights_version": int(payload.get("weights_version", 0))}

    # -- weight streaming ---------------------------------------------

    def broadcast_weights(self, params, *, version: int | None = None,
                          draft_params=None,
                          members: list[str] | None = None) -> dict:
        from concurrent.futures import ThreadPoolExecutor

        with self._lock:
            if version is not None:
                target_v = int(version)
            else:
                # Claim under the lock (see DecoderFleet.broadcast_
                # weights): racing auto-increment pushes must pick
                # distinct epochs or the loser tears the fleet.
                target_v = self._weights_latest + 1
                self._weights_latest = target_v
        # Attempt every target, dead included: an actor pod that
        # restarted behind the same DNS converges on the next push.
        live = list(self.targets)
        unknown: dict[str, str] = {}
        if members is not None:
            # Targeted-subset push (the canary path) — same contract as
            # DecoderFleet.broadcast_weights(members=...).
            known = set(live)
            unknown = {m: "unknown fleet target" for m in members
                       if m not in known}
            live = [t for t in live if t in set(members)]

        def push(addr):
            try:
                out = push_weights(addr, self.model, params, target_v,
                                   draft_params=draft_params,
                                   chunk_bytes=self.chunk_bytes,
                                   timeout=self.timeout)
                return addr, int(out.get("weights_version", target_v)), \
                    None
            except Exception as e:  # noqa: BLE001 — recorded per target
                return addr, None, e

        installed: dict[str, int] = {}
        failed: dict[str, str] = dict(unknown)
        if live:
            with ThreadPoolExecutor(max_workers=min(len(live), 16)) as pool:
                for addr, ver, err in pool.map(push, live):
                    if err is None:
                        installed[addr] = ver
                    else:
                        failed[addr] = str(err)
        with self._lock:
            self.weight_pushes += 1
            self.weight_push_failures += len(failed)
            for addr, ver in installed.items():
                self._weights_installed[addr] = max(
                    ver, self._weights_installed.get(addr, 0))
                self._dead.discard(addr)  # a landed push revives it
            if installed:
                self._weights_latest = max(self._weights_latest,
                                           max(installed.values()))
            latest = self._weights_latest
            lagging = sorted(
                t for t in self.targets if t not in self._dead
                and latest - self._weights_installed.get(t, 0) > 0)
        return {"version": target_v, "installed": installed,
                "failed": failed, "lagging": lagging}

    # -- bookkeeping ---------------------------------------------------

    def metrics(self) -> dict:
        with self._lock:
            return {
                "targets": list(self.targets),
                "dead": sorted(self._dead),
                "rollouts": self.rollouts,
                "directory_routed": self.directory_routed,
                "weight_pushes": self.weight_pushes,
                "weight_push_failures": self.weight_push_failures,
                "weights_latest": self._weights_latest,
                "weights_installed": dict(self._weights_installed),
            }

    def stop(self) -> None:
        """Remote actors have their own lifecycle (the RLJob operator
        tears the pool down); nothing to stop client-side."""
