"""Named serving scenarios — ONE registry shared by three drivers.

``bench_serving.py`` (the CLI), the CI smoke scripts, and the
ExperimentController's trials all used to carry their own copy of "drive
the decoder with a workload, report a number". This module is the single
implementation: a :class:`Scenario` couples

- a **bench** entry (``fn(args, model) -> dict``, the bench_serving
  contract: ``metric``/``value``/``unit``/``config`` keys plus a
  ``regression`` marker) for the CLI/CI path, and
- a **trial** entry (``fn(assignments, *, seed, model, quick) -> dict``)
  for the self-tuning loop: knob overrides in, objectives out. The trial
  drives the SAME serving stack the production replica runs and reads its
  objectives from the PR-7 histogram exposition through the autoscaler's
  ``scrape_signals`` reduction — a tuned config wins on the numbers the
  SLO gates actually judge, not on a bespoke stopwatch.

Trial reproducibility: every stochastic choice a trial makes (traffic
mix, prompt lengths, decode lengths) is drawn from ONE
``np.random.default_rng(seed)`` — re-running a trial with its recorded
seed observes the same trace, so a preempted trial re-runs instead of
poisoning the objective with a half-measured sample.

Each scenario also declares its **knob search space** (katib-style
parameter dicts over the engine knobs it honors) and the **checked-in
defaults** those knobs currently hold — the defaults ARE the baseline an
experiment must beat.
"""

from __future__ import annotations

import math
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Mapping

import numpy as np


def percentile(sorted_vals, p):
    """Nearest-rank percentile over an ascending list: the value at rank
    ``ceil(p/100 * n)`` (1-based). The previous ``int(n*p/100)`` index
    read one element high on exact-rank hits — p50 of an even-length
    list returned the upper middle element."""
    rank = math.ceil(len(sorted_vals) * p / 100)
    return sorted_vals[max(rank, 1) - 1]


def decode_burst_tps(d, gen, n_thr=8, rounds=3) -> float:
    """Decode-heavy tokens/s of ``n_thr`` concurrent full-length
    generations, best of ``rounds`` after an untimed warm burst. Which
    admission batch buckets the warm burst compiles depends on thread
    arrival races, so early timed rounds can still eat a stray compile;
    the best round is the steady state both paths are compared at."""
    def one(i):
        return len(d.submit([3 + (i % 7)] * 8, gen).result()["tokens"])

    with ThreadPoolExecutor(n_thr) as pool:
        list(pool.map(one, range(n_thr)))  # warm the common buckets
    best = 0.0
    for _ in range(rounds):
        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_thr) as pool:
            emitted = sum(pool.map(one, range(n_thr)))
        best = max(best, emitted / (time.perf_counter() - t0))
    return best


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Scenario:
    """One named workload. ``bench`` is the CLI/CI entry (args-driven,
    full regression gates); ``trial`` is the tuning entry (knob
    assignments in, objective vector out). Either may be None — a
    bench-only scenario can't be tuned, a trial-only one has no CLI
    flag of its own (it still runs via ``--scenario <name>``)."""

    name: str
    description: str
    bench: Callable | None = None
    trial: Callable | None = None
    # Knob search space (katib-style parameter dicts) and the checked-in
    # defaults those knobs hold today — the experiment's baseline.
    parameters: list = field(default_factory=list)
    defaults: dict = field(default_factory=dict)
    # Default objective for experiments over this scenario.
    objective: str = "tokens_per_sec"
    optimization: str = "maximize"


_REGISTRY: dict[str, Scenario] = {}


def register(scenario: Scenario) -> Scenario:
    if scenario.name in _REGISTRY:
        raise ValueError(f"scenario {scenario.name!r} already registered")
    _REGISTRY[scenario.name] = scenario
    return scenario


def get_scenario(name: str) -> Scenario:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; available {sorted(_REGISTRY)}")


def all_scenarios() -> dict[str, Scenario]:
    return dict(_REGISTRY)


def run_trial(name: str, assignments: Mapping | None = None, *,
              seed: int = 0, model: str = "lm-test-tiny",
              quick: bool = True) -> dict:
    """Run one tuning trial of ``name`` with knob ``assignments`` over
    the scenario's checked-in defaults. Returns the trial dict:
    ``objectives`` (the scrape_signals vector + throughput/KV numbers),
    ``seed``, ``assignments``, and the ThroughputBook-ingestable
    ``config``/``tokens_per_sec_per_chip`` pair."""
    sc = get_scenario(name)
    if sc.trial is None:
        raise ValueError(f"scenario {name!r} has no trial entry")
    return sc.trial(dict(assignments or {}), seed=int(seed), model=model,
                    quick=bool(quick))


# ---------------------------------------------------------------------------
# Objective plumbing: exposition text -> signal vector
# ---------------------------------------------------------------------------


def decoder_exposition(decoder) -> str:
    """The continuous decoder's metrics as ONE exposition page — the
    same families the model server serves on
    ``/monitoring/prometheus/metrics`` (histograms from the decoder's
    own registry, KV/queue gauges from its counter snapshot), so a
    trial's objective read is byte-compatible with the production
    scrape path."""
    from kubeflow_tpu.observability.metrics import render_prometheus

    m = decoder.metrics()
    return decoder.registry.render() + render_prometheus({
        "serving_requests_total": m.get("requests_admitted", 0),
        "serving_errors_total": 0,
        "serving_tokens_emitted_total": m.get("tokens_emitted", 0),
        "serving_queued": m.get("queued", 0),
        "serving_kv_bytes_in_use": m.get("kv_bytes_in_use", 0),
        "serving_kv_bytes_total": m.get("kv_bytes_total", 0),
    })


def trial_objectives(decoder, tokens_emitted: int, wall_s: float) -> dict:
    """Reduce a finished trial's decoder to the objective vector: the
    autoscaler's scrape_signals p99s (TTFT, inter-token, queue wait),
    KV fill, plus throughput and peak KV bytes."""
    from kubeflow_tpu.operators.inference import scrape_signals

    sig = scrape_signals(decoder_exposition(decoder))
    m = decoder.metrics()
    block_bytes = (m.get("kv_bytes_per_token", 0)
                   * m.get("kv_block_size", 0))
    return {
        "tokens_per_sec": round(tokens_emitted / max(wall_s, 1e-9), 2),
        "ttft_p99_s": round(sig["ttft_p99_s"], 6),
        "inter_token_p99_s": round(sig["inter_token_p99_s"], 6),
        "queue_wait_p99_s": round(sig["queue_wait_p99_s"], 6),
        "kv_utilization": round(sig["kv_utilization"], 4),
        "kv_bytes_peak": int(m.get("kv_blocks_peak", 0) * block_bytes),
        "kv_blocks_in_use_after_drain": int(m.get("kv_blocks_in_use", 0)),
    }


# ---------------------------------------------------------------------------
# decode-tps: the fast-path trial scenario
# ---------------------------------------------------------------------------

# The checked-in defaults (the baseline a tuner must beat): the paged
# pool is sized for 16 worst-case sequences and HELD CONSTANT across
# trials — tuning reapportions a fixed HBM budget (slots admitted
# against it, block granularity, prefill bucketing), it never buys more
# memory. slots=4 is today's conservative admission default.
DECODE_TPS_DEFAULTS = {
    "slots": 4,
    "kv_block_size": 16,
    "prefill_len_buckets": 0,
}

DECODE_TPS_PARAMETERS = [
    {"name": "slots", "parameterType": "int",
     "feasibleSpace": {"min": 2, "max": 16}},
    {"name": "kv_block_size", "parameterType": "int",
     "feasibleSpace": {"min": 4, "max": 24}},
    {"name": "prefill_len_buckets", "parameterType": "int",
     "feasibleSpace": {"min": 0, "max": 4}},
]

_POOL_SEQ_EQUIV = 16  # fixed pool: bytes for 16 worst-case sequences


def _decode_tps_trial(assignments: dict, *, seed: int = 0,
                      model: str = "lm-test-tiny",
                      quick: bool = True) -> dict:
    """Mixed-length decode throughput at a FIXED KV pool budget. Knobs
    reapportion the pool; seeded traffic makes a re-run observe the
    same trace."""
    import jax

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.serving.continuous import ContinuousDecoder

    knobs = {**DECODE_TPS_DEFAULTS, **assignments}
    slots = max(1, int(knobs["slots"]))
    buckets = max(0, int(knobs["prefill_len_buckets"]))

    spec = get_model(model)
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    gen = 8
    prefill_len = 40
    total = prefill_len + gen
    # Legalize the block size: the paged layout needs block | total (the
    # equal-virtual-row-width invariant). Snap DOWN to the nearest
    # divisor, so neighboring proposals land on the same legal plateau
    # rather than erroring out of the search.
    want_block = max(1, int(knobs["kv_block_size"]))
    block = max(b for b in range(1, want_block + 1) if total % b == 0)
    pool_blocks = _POOL_SEQ_EQUIV * (total // block)
    n = 24 if quick else 96
    offered = min(n, 16)

    rng = np.random.default_rng(seed)
    requests = [
        ([int(3 + rng.integers(7))] * int(rng.integers(4, 12)),
         int(rng.integers(2, gen + 1)))
        for _ in range(n)
    ]

    d = ContinuousDecoder(
        params, spec.config, slots=slots, prefill_len=prefill_len,
        max_new_tokens=gen, prefill_len_buckets=buckets,
        kv_layout="paged", kv_block_size=block,
        kv_pool_blocks=pool_blocks, stream_timeout_s=300.0)
    try:
        def one(req):
            toks, want = req
            return len(d.submit(toks, want).result(timeout=300)["tokens"])

        # Untimed warm pass over the SAME trace: compiles for every
        # admission-batch bucket this knob setting will hit land here,
        # so the timed pass measures the steady state each config is
        # compared at (not how many executables it had to build).
        with ThreadPoolExecutor(offered) as pool:
            list(pool.map(one, requests))
        t0 = time.perf_counter()
        with ThreadPoolExecutor(offered) as pool:
            emitted = sum(pool.map(one, requests))
        wall = time.perf_counter() - t0
        objectives = trial_objectives(d, emitted, wall)
    finally:
        d.stop()

    return {
        "scenario": "decode-tps",
        "seed": int(seed),
        "assignments": dict(assignments),
        "objectives": objectives,
        # ThroughputBook ingest contract (scheduler/capacity.py): the
        # profile name is the first whitespace token of ``config``.
        "config": (f"decode-tps slots{slots} block{block} "
                   f"buckets{buckets} pool{pool_blocks} n{n} seed{seed}"),
        "tokens_per_sec_per_chip": objectives["tokens_per_sec"],
    }


def _decode_tps_bench(args, model) -> dict:
    """CLI entry: the trial at the checked-in defaults, reported in the
    bench_serving artifact contract."""
    res = _decode_tps_trial({}, seed=getattr(args, "seed", 0), model=model,
                            quick=args.quick)
    obj = res["objectives"]
    return {
        "metric": "serving_decode_tps_trial_tokens_per_sec",
        "value": obj["tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": 1.0,
        "ttft_p99_ms": round(obj["ttft_p99_s"] * 1e3, 2),
        "queue_wait_p99_ms": round(obj["queue_wait_p99_s"] * 1e3, 2),
        "kv_bytes_peak": obj["kv_bytes_peak"],
        "kv_blocks_in_use_after_drain":
            obj["kv_blocks_in_use_after_drain"],
        "regression": obj["kv_blocks_in_use_after_drain"] != 0,
        "config": res["config"],
    }


# ---------------------------------------------------------------------------
# synthetic-knobs: closed-form trial for CI sweeps and policy tests
# ---------------------------------------------------------------------------

SYNTHETIC_DEFAULTS = {"slots": 4, "kv_block_size": 16}

SYNTHETIC_PARAMETERS = [
    {"name": "slots", "parameterType": "int",
     "feasibleSpace": {"min": 2, "max": 16}},
    {"name": "kv_block_size", "parameterType": "int",
     "feasibleSpace": {"min": 4, "max": 32}},
]


def _synthetic_trial(assignments: dict, *, seed: int = 0,
                     model: str = "", quick: bool = True) -> dict:
    """Closed-form objective surface over the decode-tps knob space —
    a smooth unimodal ridge whose optimum sits away from the checked-in
    defaults, plus a small seed-deterministic noise term. Instant and
    exactly reproducible: the policy-economy gates (bayesian reaching
    random's best in half the trials; monotone best traces) are judged
    here, where no wall-clock jitter can flake them."""
    knobs = {**SYNTHETIC_DEFAULTS, **assignments}
    u_slots = (float(knobs["slots"]) - 2.0) / 14.0
    u_block = (float(knobs["kv_block_size"]) - 4.0) / 28.0
    ridge = math.exp(-((u_slots - 0.75) ** 2
                       + (u_block - 0.40) ** 2) / 0.18)
    noise = float(np.random.default_rng(
        seed * 1_000_003 + int(knobs["slots"]) * 31
        + int(knobs["kv_block_size"])).normal(0.0, 0.003))
    tps = round(100.0 * ridge + noise, 4)
    return {
        "scenario": "synthetic-knobs",
        "seed": int(seed),
        "assignments": dict(assignments),
        "objectives": {
            "tokens_per_sec": tps,
            "ttft_p99_s": round(0.05 / (0.2 + ridge), 6),
            "inter_token_p99_s": round(0.01 / (0.2 + ridge), 6),
            "queue_wait_p99_s": 0.0,
            "kv_utilization": round(min(u_slots + 0.1, 1.0), 4),
            "kv_bytes_peak": int(4096 * (1 + u_block)),
            "kv_blocks_in_use_after_drain": 0,
        },
        "config": (f"synthetic-knobs slots{knobs['slots']} "
                   f"block{knobs['kv_block_size']} seed{seed}"),
        "tokens_per_sec_per_chip": tps,
    }


# ---------------------------------------------------------------------------
# Scenario implementations shared with bench_serving.py
# ---------------------------------------------------------------------------


def bench_prefix_reuse(args, model) -> dict:
    """Prefix-reuse scenario: N concurrent requests sharing an S-token
    system prompt, decoded greedily through the continuous decoder with
    the prefix cache ON vs OFF. Reports TTFT, prefill dispatch/token
    volume, and the cache counters; emitted tokens must be identical
    both ways (``regression`` flags a mismatch or a <2x volume win)."""
    import jax

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.serving.continuous import ContinuousDecoder

    spec = get_model(model)
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    n = 16 if args.quick else max(16, args.requests // 8)
    gen = min(args.max_new_tokens, 8)
    system = list(range(3, 3 + args.prefix_len))  # the shared prefix
    prompts = [system + [200 + i, 17, 11 + (i % 5)] for i in range(n)]
    prefill_len = max(args.seq_len, args.prefix_len + 8)

    runs = {}
    for label, cache_slots in (("off", 0), ("on", 8)):
        d = ContinuousDecoder(
            params, spec.config, slots=8, prefill_len=prefill_len,
            max_new_tokens=gen, prefix_cache_slots=cache_slots,
            prefix_cache_min_len=16, prefill_len_buckets=3)
        try:
            if cache_slots:
                # Preload the shared system prompt (what a production
                # deployment does at startup) so every request hits.
                d.prime_prefix(system)
            # Warm the compiled admission shapes outside the timed burst.
            d.generate(prompts[0][:4], 1)

            def one(p):
                h = d.submit(p, gen)
                res = h.result(timeout=300)
                return res["tokens"], h.ttft_s * 1e3
            with ThreadPoolExecutor(args.concurrency) as pool:
                results = list(pool.map(one, prompts))
            m = d.metrics()
        finally:
            d.stop()
        runs[label] = {
            "tokens": [t for t, _ in results],
            "ttft_p50_ms": round(percentile(
                sorted(ms for _, ms in results), 50), 2),
            "prefill_dispatches": m["prefill_dispatches"],
            "prefill_tokens": m["prefill_tokens"],
            "prefix_hits": m["prefix_hits"],
            "prefix_tokens_reused": m["prefix_tokens_reused"],
        }

    identical = runs["on"]["tokens"] == runs["off"]["tokens"]
    ratio = runs["off"]["prefill_tokens"] / max(
        runs["on"]["prefill_tokens"], 1)
    return {
        "metric": "serving_prefix_reuse_ttft_p50_ms",
        "value": runs["on"]["ttft_p50_ms"],
        "unit": "ms",
        "vs_baseline": 1.0,
        "ttft_off_p50_ms": runs["off"]["ttft_p50_ms"],
        "prefill_tokens_off": runs["off"]["prefill_tokens"],
        "prefill_tokens_on": runs["on"]["prefill_tokens"],
        "prefill_volume_ratio": round(ratio, 2),
        "prefill_dispatches_off": runs["off"]["prefill_dispatches"],
        "prefill_dispatches": runs["on"]["prefill_dispatches"],
        "prefix_hits": runs["on"]["prefix_hits"],
        "prefix_tokens_reused": runs["on"]["prefix_tokens_reused"],
        "tokens_identical": identical,
        "regression": (not identical) or ratio < 2.0,
        "config": f"{model} prefix{args.prefix_len} n{n} gen{gen} "
                  f"prefill{prefill_len} c{args.concurrency}",
    }


def bench_speculative(args, model) -> dict:
    """Speculative-decoding scenario: N concurrent greedy requests through
    the continuous decoder with speculation off / n-gram / draft-model.
    Tokens must be byte-identical in every mode (speculation may only
    change cost); the draft-model run (same weights, so acceptance is
    structural, not luck) must clear >1.5 accepted tokens per verify
    dispatch — the dispatch economy that motivates the feature."""
    import jax

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.serving.continuous import ContinuousDecoder

    spec = get_model(model)
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    n = 8 if args.quick else max(8, args.requests // 16)
    gen = min(args.max_new_tokens, 16)
    k = args.speculative_k
    # Mildly repetitive prompts: gives the n-gram proposer something to
    # find without rigging the model's own continuations.
    prompts = [([3 + i, 17, 29, 3 + i, 17] * 3)[:12] for i in range(n)]

    runs = {}
    modes = (("off", {}),
             ("ngram", {"speculative_k": k, "draft_mode": "ngram"}),
             ("draft_model", {"speculative_k": k,
                              "draft_mode": f"model:{model}"}))
    for label, kw in modes:
        d = ContinuousDecoder(params, spec.config, slots=8, prefill_len=32,
                              max_new_tokens=gen, **kw)
        try:
            d.generate(prompts[0][:4], 1)  # warm the compiled shapes

            def one(p):
                h = d.submit(p, gen)
                return h.result(timeout=300)["tokens"]
            t0 = time.perf_counter()
            with ThreadPoolExecutor(args.concurrency) as pool:
                tokens = list(pool.map(one, prompts))
            wall = time.perf_counter() - t0
            m = d.metrics()
        finally:
            d.stop()
        runs[label] = {
            "tokens": tokens,
            "wall_s": wall,
            "decode_dispatches": m["decode_dispatches"],
            "spec_drafted_tokens": m["spec_drafted_tokens"],
            "spec_accepted_tokens": m["spec_accepted_tokens"],
            "spec_verify_dispatches": m["spec_verify_dispatches"],
            "spec_draft_dispatches": m["spec_draft_dispatches"],
            "spec_acceptance_rate": round(m["spec_acceptance_rate"], 3),
        }

    identical = (runs["ngram"]["tokens"] == runs["off"]["tokens"]
                 and runs["draft_model"]["tokens"] == runs["off"]["tokens"])
    dm = runs["draft_model"]
    accepted_per_dispatch = (dm["spec_accepted_tokens"]
                             / max(dm["spec_verify_dispatches"], 1))
    return {
        "metric": "serving_spec_accepted_tokens_per_dispatch",
        "value": round(accepted_per_dispatch, 2),
        "unit": "tokens/dispatch",
        "vs_baseline": 1.0,
        "acceptance_rate": dm["spec_acceptance_rate"],
        "ngram_acceptance_rate": runs["ngram"]["spec_acceptance_rate"],
        "ngram_accepted_tokens": runs["ngram"]["spec_accepted_tokens"],
        "drafted_tokens": dm["spec_drafted_tokens"],
        "accepted_tokens": dm["spec_accepted_tokens"],
        "verify_dispatches": dm["spec_verify_dispatches"],
        "draft_dispatches": dm["spec_draft_dispatches"],
        "decode_dispatches_off": runs["off"]["decode_dispatches"],
        "decode_dispatches_on": dm["decode_dispatches"],
        "tokens_identical": identical,
        "regression": (not identical) or accepted_per_dispatch <= 1.5,
        "config": f"{model} k{k} n{n} gen{gen} c{args.concurrency}",
    }


def bench_concurrency_sweep(args, model) -> dict:
    """Dense vs paged KV at EQUAL total pool bytes under an offered-
    concurrency ladder of mixed-length greedy requests.

    The dense decoder reserves ``slots * total_len`` positions, so its
    in-flight ceiling is ``slots`` no matter how short the requests are.
    The paged decoder gets the SAME pool bytes (``slots * total_len /
    block_size`` blocks) but 4x the slots: admission is bounded by
    tokens resident, so the mixed-length load packs more concurrent
    requests into the identical HBM budget. A sequential probe pins
    byte-identical greedy outputs between layouts; the regression marker
    fires on divergence, on a paged in-flight peak below 2x dense, or on
    leaked blocks after drain."""
    import jax

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.serving.continuous import ContinuousDecoder

    spec = get_model(model)
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    gen = min(args.max_new_tokens, 16)
    prefill_len = 32
    block = 8
    total = prefill_len + gen
    dense_slots = 4
    pool_blocks = dense_slots * (total // block)  # equal KV bytes
    cfg = spec.config
    bytes_per_token = (2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
                       * np.dtype(cfg.dtype).itemsize)
    ladder = [4, 16] if args.quick else [4, 16, 64]

    def request(i):
        plen = (4, 6, 8, 10)[i % 4]
        want = (2, 3, 4, gen // 2)[i % 4]
        return [3 + (i % 7)] * plen, want

    probes = [[1, 2, 3], [7, 5], [9, 9, 9, 9, 2]]
    runs = {}
    for layout in ("dense", "paged"):
        kw = (dict(kv_layout="paged", kv_block_size=block,
                   kv_pool_blocks=pool_blocks)
              if layout == "paged" else {})
        slots = dense_slots * 4 if layout == "paged" else dense_slots
        d = ContinuousDecoder(params, spec.config, slots=slots,
                              prefill_len=prefill_len, max_new_tokens=gen,
                              prefill_len_buckets=2,
                              stream_timeout_s=300.0, **kw)
        try:
            # Sequential parity probe (also warms compiled shapes):
            # layout must never change tokens.
            probe_out = [d.generate(p, 4)["tokens"] for p in probes]
            levels = {}
            for n in ladder:
                t0 = time.perf_counter()

                def one(i):
                    toks, want = request(i)
                    return len(d.submit(toks, want).result()["tokens"])
                with ThreadPoolExecutor(n) as pool:
                    emitted = sum(pool.map(one, range(n)))
                wall = time.perf_counter() - t0
                levels[n] = round(emitted / wall, 1)
            m = d.metrics()
        finally:
            d.stop()
        runs[layout] = {
            "tokens": probe_out,
            "levels": levels,
            "peak_in_flight": m["peak_in_flight"],
            "kv_blocks_peak": m["kv_blocks_peak"],
            "kv_blocks_in_use": m["kv_blocks_in_use"],
            "defer_admissions": m["kv_defer_admissions"],
            "kv_peak_bytes": (
                m["kv_blocks_peak"] * block * bytes_per_token
                if layout == "paged"
                else slots * total * bytes_per_token),
        }

    identical = runs["paged"]["tokens"] == runs["dense"]["tokens"]
    leak = runs["paged"]["kv_blocks_in_use"]
    dense_peak = runs["dense"]["peak_in_flight"]
    paged_peak = runs["paged"]["peak_in_flight"]
    top = ladder[-1]
    return {
        "metric": "serving_paged_peak_in_flight",
        "value": paged_peak,
        "unit": "requests",
        "vs_baseline": 1.0,
        "dense_peak_in_flight": dense_peak,
        "concurrency_ratio": round(paged_peak / max(dense_peak, 1), 2),
        "tokens_per_sec_dense": runs["dense"]["levels"],
        "tokens_per_sec_paged": runs["paged"]["levels"],
        "pool_bytes": pool_blocks * block * bytes_per_token,
        "kv_peak_bytes_dense": runs["dense"]["kv_peak_bytes"],
        "kv_peak_bytes_paged": runs["paged"]["kv_peak_bytes"],
        "defer_admissions": runs["paged"]["defer_admissions"],
        "kv_blocks_in_use_after_drain": leak,
        "tokens_identical": identical,
        "regression": ((not identical) or leak != 0
                       or paged_peak < 2 * dense_peak),
        "config": f"{model} ladder{ladder} gen{gen} "
                  f"prefill{prefill_len} block{block} "
                  f"pool{pool_blocks} slots{dense_slots}v"
                  f"{dense_slots * 4} top{top}",
    }


# ---------------------------------------------------------------------------
# Registrations
# ---------------------------------------------------------------------------

register(Scenario(
    name="decode-tps",
    description="Mixed-length decode throughput at a fixed KV pool "
                "budget; knobs reapportion the pool (slots, block size, "
                "prefill bucketing).",
    bench=_decode_tps_bench,
    trial=_decode_tps_trial,
    parameters=DECODE_TPS_PARAMETERS,
    defaults=dict(DECODE_TPS_DEFAULTS),
    objective="tokens_per_sec",
    optimization="maximize",
))

register(Scenario(
    name="synthetic-knobs",
    description="Closed-form objective over the decode-tps knob space; "
                "instant and seed-deterministic (policy-economy gates "
                "and CI sweeps are judged here).",
    trial=_synthetic_trial,
    parameters=SYNTHETIC_PARAMETERS,
    defaults=dict(SYNTHETIC_DEFAULTS),
    objective="tokens_per_sec",
    optimization="maximize",
))

register(Scenario(
    name="prefix-reuse",
    description="Shared-system-prompt TTFT and prefill volume, prefix "
                "cache on vs off (byte-identical tokens required).",
    bench=bench_prefix_reuse,
))

register(Scenario(
    name="speculative",
    description="Speculative decoding off / n-gram / draft-model: "
                "acceptance economy at byte-identical greedy tokens.",
    bench=bench_speculative,
))

register(Scenario(
    name="concurrency-sweep",
    description="Dense vs paged KV at equal pool bytes under an "
                "offered-concurrency ladder.",
    bench=bench_concurrency_sweep,
))
