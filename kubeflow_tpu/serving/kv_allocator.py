"""Host-side block allocator for the paged KV cache.

The paged decode layout (models/decode.py:init_paged_state) stores K/V in
a device pool of fixed-size blocks instead of one dense
``[slots, total_len]`` row per decode slot; this module is the host half
that decides *which* physical blocks back each slot's virtual positions.
It is deliberately dumb and auditable:

- a **free list** of physical block ids (LIFO, so hot blocks are reused
  while still cache-resident),
- a **refcount** per block. ``alloc`` hands out blocks at refcount 1;
  ``share`` bumps a live block (zero-copy prefix reuse: a prefix-cache
  hit maps the donor's full blocks straight into the new slot's table);
  ``free`` drops a reference and returns the block to the free list when
  the last holder lets go.

Every transition is guarded: sharing a free block or freeing a block
below refcount zero raises instead of silently corrupting the pool — the
serving invariants ("no block is referenced by two live slots unless
refcounted-shared", "every block is freed exactly once") are enforced
here, at the single choke point, rather than re-derived at each call
site.

Pure host logic — no jax imports — so the allocator is unit-testable
without a device and safe to mutate under the decoder's prefix lock.
"""

from __future__ import annotations

# One float32 abs-max scale per (layer, position, kv head) rides each
# int8 payload byte stream — the scale pool is indexed by the SAME block
# ids, so every refcount transition below covers payload and scales as
# one unit.
KV_SCALE_BYTES = 4


def kv_bytes_per_token(n_layers: int, n_kv_heads: int, head_dim: int,
                       fp_bytes: int, kv_dtype: str = "fp",
                       tp_shards: int = 1) -> int:
    """HBM bytes one resident K+V position costs in the paged pool,
    PER CHIP.

    ``fp``: ``2 * L * Hkv * hd * fp_bytes``. ``int8``: the payload drops
    to one byte per element but each (position, head) carries a
    :data:`KV_SCALE_BYTES` scale, so the per-head cost is
    ``hd + KV_SCALE_BYTES`` — the honest number an autoscaler must see
    (scale overhead is why int8 is ~``fp_bytes * hd / (hd + 4)``x, not
    exactly ``fp_bytes``x, denser).

    ``tp_shards``: a tensor-parallel replica shards the pool over the
    KV-head axis, so each of its chips holds ``Hkv / tp`` heads per
    position. The pool-fill gauges priced off this number must reflect
    real per-chip HBM — a tp=4 replica whose gauges reported the
    host-global (summed) bytes would look 4x fuller than any of its
    chips actually is, and the autoscaler and gateway spill would
    misread the pool."""
    if tp_shards < 1:
        raise ValueError(f"tp_shards must be >= 1, got {tp_shards}")
    if n_kv_heads % tp_shards:
        raise ValueError(
            f"{n_kv_heads} kv heads not divisible by tp_shards "
            f"{tp_shards}")
    if kv_dtype == "int8":
        per_head = head_dim + KV_SCALE_BYTES
    elif kv_dtype in ("", "fp"):
        per_head = head_dim * fp_bytes
    else:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
    return 2 * n_layers * (n_kv_heads // tp_shards) * per_head


class BlockAllocator:
    """Free list + refcounts over ``num_blocks`` physical KV blocks.

    ``bytes_per_token`` (set by the owner from
    :func:`kv_bytes_per_token`) prices the pool in real HBM bytes so
    stats consumers — the Prometheus gauges the ROADMAP-1 autoscaler
    scales on — see bytes resident, not just block counts whose meaning
    shifts with ``kv_dtype``."""

    def __init__(self, num_blocks: int, block_size: int,
                 bytes_per_token: int = 0):
        if num_blocks <= 0:
            raise ValueError("BlockAllocator needs at least one block")
        if block_size <= 0:
            raise ValueError("block_size must be positive")
        if bytes_per_token < 0:
            raise ValueError("bytes_per_token must be >= 0")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.bytes_per_token = bytes_per_token
        # LIFO free list: ascending ids pop first (determinism helps the
        # byte-identity tests pin block placement).
        self._free = list(range(num_blocks - 1, -1, -1))
        self._refs = [0] * num_blocks

    # -- introspection -------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def bytes_in_use(self) -> int:
        """HBM bytes currently claimed (0 when unpriced)."""
        return self.blocks_in_use * self.block_size * self.bytes_per_token

    @property
    def bytes_total(self) -> int:
        """HBM bytes of the whole pool (0 when unpriced)."""
        return self.num_blocks * self.block_size * self.bytes_per_token

    def ref_count(self, block: int) -> int:
        return self._refs[block]

    def blocks_for(self, tokens: int) -> int:
        """Worst-case block count for ``tokens`` KV positions (>= 1, so a
        zero-token degenerate request still reserves a write target)."""
        return max(1, -(-int(tokens) // self.block_size))

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    # -- transitions ---------------------------------------------------

    def alloc(self, n: int) -> list[int]:
        """Claim ``n`` blocks at refcount 1. Raises ``MemoryError`` when
        the pool cannot serve the request — callers gate on
        :meth:`can_alloc` under their lock, so hitting this means a
        bookkeeping bug, not backpressure."""
        if n > len(self._free):
            raise MemoryError(
                f"requested {n} KV blocks but only {len(self._free)} of "
                f"{self.num_blocks} are free"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def share(self, block: int) -> None:
        """Add a reference to a LIVE block (zero-copy prefix sharing)."""
        if self._refs[block] <= 0:
            raise ValueError(f"sharing free block {block}")
        self._refs[block] += 1

    def free(self, block: int) -> None:
        """Drop one reference; the last drop returns the block to the
        free list. Freeing an already-free block raises — a double free
        would let two slots scribble over each other's KV."""
        if self._refs[block] <= 0:
            raise ValueError(f"double free of block {block}")
        self._refs[block] -= 1
        if self._refs[block] == 0:
            self._free.append(block)
