"""Dynamic batcher.

Requests queue up to ``batch_timeout_ms`` or until the server batch fills,
then run as one TPU call — the role TF-Serving's batching config plays in the
reference (enable via the prototype param, tf-serving-template.libsonnet).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass
class _Pending:
    instance: dict
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Exception | None = None


class DynamicBatcher:
    def __init__(
        self,
        predict_batch: Callable[[list[dict]], list[dict]],
        batch_size: int,
        batch_timeout_ms: float = 5.0,
    ):
        self._predict = predict_batch
        self._batch_size = batch_size
        self._timeout = batch_timeout_ms / 1000.0
        self._queue: queue.Queue[_Pending] = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            deadline = time.monotonic() + self._timeout
            while len(batch) < self._batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                results = self._predict([p.instance for p in batch])
                for p, r in zip(batch, results):
                    p.result = r
            except Exception as e:  # surfaced to every waiter in the batch
                for p in batch:
                    p.error = e
            for p in batch:
                p.event.set()

    def submit_async(self, instance: dict) -> _Pending:
        """Enqueue without waiting — lets a caller enqueue a whole request's
        instances first so they coalesce into full batches, then collect."""
        p = _Pending(instance)
        self._queue.put(p)
        return p

    @staticmethod
    def collect(p: _Pending, timeout: float = 30.0) -> dict:
        if not p.event.wait(timeout):
            raise TimeoutError("predict timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def submit(self, instance: dict, timeout: float = 30.0) -> dict:
        return self.collect(self.submit_async(instance), timeout)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
