"""Dynamic batcher.

Requests queue up to ``batch_timeout_ms`` or until the server batch fills,
then run as one TPU call — the role TF-Serving's batching config plays in the
reference (enable via the prototype param, tf-serving-template.libsonnet).

``batch_timeout_ms`` is a batch-START deadline, not a per-get wait: the
window runs from the moment the batch's oldest member was SUBMITTED, so
time an item spent queued behind a previous batch's predict counts
against it — an already-expired deadline flushes whatever is queued right
now instead of holding the line another full window. ``stop()`` drains:
the loop keeps predicting until the queue is empty, and anything still
queued after the join fails fast with an error rather than leaving its
waiter to hit the collect timeout.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass
class _Pending:
    instance: dict
    submitted: float = field(default_factory=time.monotonic)
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Exception | None = None


class DynamicBatcher:
    def __init__(
        self,
        predict_batch: Callable[[list[dict]], list[dict]],
        batch_size: int,
        batch_timeout_ms: float = 5.0,
    ):
        self._predict = predict_batch
        self._batch_size = batch_size
        self._timeout = batch_timeout_ms / 1000.0
        self._queue: queue.Queue[_Pending] = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return  # queue drained: stop() can join
                continue
            batch = [first]
            # Deadline anchored at the oldest member's SUBMIT time: an
            # item that already waited out the window behind a previous
            # batch flushes immediately (with whatever else is queued).
            deadline = first.submitted + self._timeout
            while len(batch) < self._batch_size and not self._stop.is_set():
                remaining = deadline - time.monotonic()
                try:
                    # Cap each wait so a stop() mid-window is honored
                    # promptly; remaining <= 0 degrades to a non-blocking
                    # drain of what's already queued.
                    batch.append(
                        self._queue.get(timeout=min(max(remaining, 0.0),
                                                    0.05))
                    )
                except queue.Empty:
                    if remaining <= 0:
                        break
            try:
                results = self._predict([p.instance for p in batch])
                for p, r in zip(batch, results):
                    p.result = r
            except Exception as e:  # surfaced to every waiter in the batch
                for p in batch:
                    p.error = e
            for p in batch:
                p.event.set()

    def submit_async(self, instance: dict) -> _Pending:
        """Enqueue without waiting — lets a caller enqueue a whole request's
        instances first so they coalesce into full batches, then collect."""
        if self._stop.is_set():
            raise RuntimeError("batcher stopped")
        p = _Pending(instance)
        self._queue.put(p)
        return p

    @staticmethod
    def collect(p: _Pending, timeout: float = 30.0) -> dict:
        if not p.event.wait(timeout):
            raise TimeoutError("predict timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def submit(self, instance: dict, timeout: float = 30.0) -> dict:
        return self.collect(self.submit_async(instance), timeout)

    def stop(self) -> None:
        """Stop accepting work, drain the queue (the loop predicts what it
        can; the backstop below errors the rest), and join the thread."""
        self._stop.set()
        self._thread.join(timeout=5)
        err = RuntimeError("batcher stopped")
        while True:
            try:
                p = self._queue.get_nowait()
            except queue.Empty:
                break
            p.error = err
            p.event.set()
