"""Live weight-push envelopes for the serving fleet.

A learner pushes fresh params into running decoders
(:meth:`~kubeflow_tpu.serving.continuous.ContinuousDecoder.update_weights`);
this module is the host-side wire format around that push — the weights
sibling of :mod:`kubeflow_tpu.serving.handoff`:

- in process (``DecoderFleet.broadcast_weights``) the pytree travels as
  plain arrays — zero copies beyond the device fetch the learner already
  paid;
- across the HTTP fleet, :func:`pack_weights` splits the flattened tree
  into size-bounded CHUNKS of base64-encoded leaves (a model is orders
  of magnitude bigger than a KV handoff — one monolithic JSON body would
  stall the server's accept loop and double peak host memory), each
  chunk a self-describing versioned envelope POSTed at the model
  server's ``:weights`` endpoint. The server assembles chunks per
  weights epoch (:class:`WeightChunkAssembler`) and installs the tree
  atomically only when the LAST chunk lands — a half-received push can
  never install. Weight bytes travel server-to-server (learner → each
  replica), never through the gateway.

Leaves are keyed by their pytree path (``parallel.sharding.path_str``
spelling), and the receiver rebuilds the tree against its OWN serving
params' structure — paths it doesn't recognize, or a push that doesn't
cover every serving leaf, fail loudly instead of installing a torn tree.

Pure host logic — numpy only, no jax — importable by learners and tests
without the serving stack's device deps.
"""

from __future__ import annotations

import base64
import json
from http.client import HTTPConnection

import numpy as np

# Envelope schema version: receivers reject anything newer rather than
# guess at a layout (a mis-parsed push would install garbage weights).
WEIGHTS_ENVELOPE_VERSION = 1

# Default chunk payload bound. Small enough that a chunk never stalls a
# model server's HTTP thread for long; large enough that tiny models
# ship in one POST.
DEFAULT_CHUNK_BYTES = 8 << 20


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency, always present here

        return np.dtype(getattr(ml_dtypes, name))


def _pack_array(arr) -> dict:
    a = np.asarray(arr)
    return {
        "dtype": a.dtype.name,
        "shape": list(a.shape),
        "data": base64.b64encode(np.ascontiguousarray(a).tobytes())
        .decode("ascii"),
    }


def _unpack_array(d: dict) -> np.ndarray:
    if not isinstance(d, dict) or "data" not in d:
        raise ValueError("malformed weights array")
    raw = base64.b64decode(d["data"])
    arr = np.frombuffer(raw, dtype=_np_dtype(d["dtype"]))
    return arr.reshape([int(s) for s in d["shape"]])


def flatten_params(params) -> dict[str, np.ndarray]:
    """``path -> host array`` for a param pytree (the path spelling of
    ``parallel.sharding.path_str``, so envelopes and receivers agree).
    Device leaves are fetched to host; paths are unique by construction
    (pytree paths are)."""
    import jax

    from kubeflow_tpu.parallel.sharding import path_str

    leaves = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        leaves[path_str(kp)] = np.asarray(jax.device_get(leaf))
    return leaves


def unflatten_params(leaves: dict[str, np.ndarray], reference):
    """Rebuild a pytree shaped like ``reference`` from a ``path ->
    array`` map. Raises ``ValueError`` when the push does not cover the
    reference's leaves exactly — a partial tree must never install."""
    import jax

    from kubeflow_tpu.parallel.sharding import path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(reference)
    want = [path_str(kp) for kp, _ in flat]
    missing = [p for p in want if p not in leaves]
    extra = sorted(set(leaves) - set(want))
    if missing or extra:
        raise ValueError(
            f"weights push does not match the serving tree: "
            f"missing={missing[:3]}{'...' if len(missing) > 3 else ''} "
            f"extra={extra[:3]}{'...' if len(extra) > 3 else ''}")
    return jax.tree_util.tree_unflatten(
        treedef, [leaves[p] for p in want])


def plan_chunks(items: list[tuple[str, np.ndarray]],
                chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                ) -> list[list[tuple[str, np.ndarray]]]:
    """Split flattened ``(path, array)`` items into chunk groups on
    leaf boundaries by cumulative payload size. Shared by the push
    packer and the donor-side pull export, so both directions of the
    transport agree on chunk count for a given tree."""
    groups: list[list[tuple[str, np.ndarray]]] = [[]]
    size = 0
    for path, arr in items:
        nbytes = int(arr.nbytes)
        if groups[-1] and size + nbytes > max(1, int(chunk_bytes)):
            groups.append([])
            size = 0
        groups[-1].append((path, arr))
        size += nbytes
    return groups


def flatten_namespaced(params, draft_params=None,
                       ) -> list[tuple[str, np.ndarray]]:
    """Flatten a (params, draft) pair into the namespaced ``m/``/``d/``
    item list every envelope carries."""
    items = [("m/" + p, a) for p, a in flatten_params(params).items()]
    if draft_params is not None:
        items += [("d/" + p, a)
                  for p, a in flatten_params(draft_params).items()]
    return items


def pack_chunk(group: list[tuple[str, np.ndarray]], weights_version: int,
               seq: int, total: int, has_draft: bool) -> dict:
    """One chunk group → its self-describing envelope."""
    return {
        "version": WEIGHTS_ENVELOPE_VERSION,
        "weights_version": int(weights_version),
        "seq": int(seq),
        "chunks": int(total),
        "has_draft": bool(has_draft),
        "leaves": {p: _pack_array(a) for p, a in group},
    }


def pack_weights(params, weights_version: int, *,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 draft_params=None) -> list[dict]:
    """Split ``params`` into one or more JSON-safe chunk envelopes.

    Chunks split on leaf boundaries by cumulative payload size; every
    chunk carries ``(weights_version, seq, chunks)`` so the receiver
    can assemble exactly one epoch at a time and discard a superseded
    partial push. ``draft_params`` (a paired draft model's tree) rides
    the same envelopes under a separate namespace, so target and draft
    install in the same epoch."""
    items = flatten_namespaced(params, draft_params)
    groups = plan_chunks(items, chunk_bytes)
    return [pack_chunk(group, weights_version, seq, len(groups),
                       draft_params is not None)
            for seq, group in enumerate(groups)]


def unpack_chunk(env: dict) -> dict:
    """Decode one chunk envelope. Raises ``ValueError`` on a malformed
    or version-mismatched envelope — the server answers 400 instead of
    assembling garbage."""
    if not isinstance(env, dict) or \
            env.get("version") != WEIGHTS_ENVELOPE_VERSION:
        raise ValueError(
            f"unsupported weights envelope version="
            f"{env.get('version') if isinstance(env, dict) else env!r}")
    try:
        wv = int(env["weights_version"])
        seq = int(env["seq"])
        total = int(env["chunks"])
    except (KeyError, TypeError, ValueError):
        raise ValueError("weights envelope missing version/seq/chunks"
                         ) from None
    if not 0 <= seq < total:
        raise ValueError(f"weights chunk seq {seq} outside 0..{total - 1}")
    leaves = env.get("leaves")
    if not isinstance(leaves, dict):
        raise ValueError("weights envelope carries no leaves")
    return {
        "weights_version": wv, "seq": seq, "chunks": total,
        "has_draft": bool(env.get("has_draft")),
        "leaves": {str(p): _unpack_array(a) for p, a in leaves.items()},
    }


class WeightChunkAssembler:
    """Per-epoch chunk assembly on the receiving server.

    Chunks of ONE weights epoch accumulate until all arrive; then
    :meth:`add` returns the complete ``(leaves, has_draft)`` and resets.
    A chunk for a NEWER epoch discards any partial older one (the
    straggler learner lost the race; it converges on the next push); a
    chunk for an older epoch than the assembling one is rejected as
    stale. Callers serialize access (the model server wraps calls in
    its own lock)."""

    def __init__(self) -> None:
        self._version: int | None = None
        self._chunks: int = 0
        self._seen: set[int] = set()
        self._leaves: dict[str, np.ndarray] = {}
        self._has_draft = False

    @property
    def pending(self) -> int:
        """Chunks still missing for the epoch being assembled."""
        return self._chunks - len(self._seen) if self._seen else 0

    def add(self, chunk: dict) -> tuple[dict, bool] | None:
        wv = chunk["weights_version"]
        if self._version is not None and wv < self._version:
            raise ValueError(
                f"stale weights chunk for epoch {wv}; assembling "
                f"{self._version}")
        if self._version != wv:
            self._version = wv
            self._chunks = chunk["chunks"]
            self._seen = set()
            self._leaves = {}
            self._has_draft = chunk["has_draft"]
        if chunk["chunks"] != self._chunks:
            raise ValueError(
                f"weights chunk count changed mid-push "
                f"({chunk['chunks']} != {self._chunks})")
        if chunk["seq"] in self._seen:
            return None  # duplicate delivery: idempotent
        self._seen.add(chunk["seq"])
        self._leaves.update(chunk["leaves"])
        if len(self._seen) < self._chunks:
            return None
        leaves, has_draft = self._leaves, self._has_draft
        self._version, self._chunks = None, 0
        self._seen, self._leaves = set(), {}
        return leaves, has_draft


def split_namespaces(leaves: dict) -> tuple[dict, dict]:
    """Split assembled leaves into (model, draft) path maps (the
    ``m/``/``d/`` namespaces :func:`pack_weights` writes)."""
    model = {p[2:]: a for p, a in leaves.items() if p.startswith("m/")}
    draft = {p[2:]: a for p, a in leaves.items() if p.startswith("d/")}
    return model, draft


def push_weights(target: str, model: str, params, weights_version: int,
                 *, draft_params=None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                 timeout: float = 60.0) -> dict:
    """POST a param pytree at ``target``'s ``:weights`` endpoint
    chunk-by-chunk (``target`` = ``host:port`` of a model server —
    learner-to-server direct, never through the gateway). Returns the
    final chunk's response dict ({"installed": bool, "weights_version":
    int}). Raises ``OSError``/``ValueError`` on transport or protocol
    failure — the caller (broadcast, operator) owns retry policy."""
    host, _, port_s = target.partition(":")
    out: dict = {}
    for env in pack_weights(params, weights_version,
                            chunk_bytes=chunk_bytes,
                            draft_params=draft_params):
        data = json.dumps(env).encode()
        conn = HTTPConnection(host, int(port_s or 80), timeout=timeout)
        try:
            conn.request("POST", f"/v1/models/{model}:weights",
                         body=data,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise ValueError(
                    f"weights push chunk {env['seq']} refused: "
                    f"HTTP {resp.status} {body[:200]!r}")
            out = json.loads(body or b"{}")
        finally:
            conn.close()
    return out


def pull_weights(source: str, model: str, *,
                 timeout: float = 60.0) -> tuple[dict, int, bool]:
    """Pull a donor replica's param pytree over the chunked envelope —
    the PR-15 transport's new direction (replica birth): a NEWBORN asks
    a serving peer for its weights instead of touching the checkpoint
    store on the hot path.

    POSTs ``{"seq": k}`` at ``source``'s ``:pull`` endpoint chunk by
    chunk and assembles through :class:`WeightChunkAssembler`, so the
    epoch-consistency rules are the push path's exactly: every chunk
    carries the donor's weights epoch, a push landing ON THE DONOR
    mid-pull bumps the epoch and the assembler discards the partial
    older tree (the pull restarts at the new epoch — a mixed-epoch
    install is impossible by construction), and the assembled tree is
    complete or nothing.

    Returns ``(leaves, weights_version, has_draft)`` — namespaced
    leaves ready for :func:`split_namespaces`. Raises ``OSError`` /
    ``ValueError`` on a dead or misbehaving donor; the caller
    (engine birth) owns the donor-list fallback."""
    host, _, port_s = source.partition(":")
    seq = 0
    version: int | None = None
    asm = WeightChunkAssembler()
    # 2 epoch restarts tolerated: a rollout storm pushing faster than a
    # pull can drain is a donor to give up on, not to chase forever.
    restarts = 0
    while True:
        data = json.dumps({"seq": seq}).encode()
        conn = HTTPConnection(host, int(port_s or 80), timeout=timeout)
        try:
            conn.request("POST", f"/v1/models/{model}:pull", body=data,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise ValueError(
                    f"weights pull chunk {seq} refused: "
                    f"HTTP {resp.status} {body[:200]!r}")
            env = json.loads(body)
        finally:
            conn.close()
        chunk = unpack_chunk(env)
        if version is not None and chunk["weights_version"] != version:
            # The donor swapped epochs mid-pull: restart at chunk 0 of
            # the new epoch (the assembler already dropped the partial).
            restarts += 1
            if restarts > 2:
                raise ValueError(
                    f"donor {source} kept swapping weights epochs "
                    f"mid-pull ({version} -> {chunk['weights_version']})")
            version = chunk["weights_version"]
            done = asm.add(chunk) if chunk["seq"] == 0 else None
            seq = 1 if chunk["seq"] == 0 else 0
            if done is not None:
                leaves, has_draft = done
                return leaves, version, has_draft
            continue
        version = chunk["weights_version"]
        done = asm.add(chunk)
        if done is not None:
            leaves, has_draft = done
            return leaves, version, has_draft
        seq += 1
        if seq >= chunk["chunks"]:
            raise ValueError(
                f"donor {source} never completed epoch {version}: "
                f"{asm.pending} chunks still missing after a full sweep")
