"""REST model server.

The http-proxy surface (components/k8s-model-server/http-proxy/server.py:
PredictHandler :251, metadata :154) served directly from the TPU process:

- ``POST /v1/models/<name>:predict``  {"instances": [...]} → {"predictions": [...]}
- ``GET  /v1/models/<name>``          model metadata + availability
- ``GET  /healthz`` ``GET /readyz``   liveness/readiness (probe target,
  tf-serving-template.libsonnet:70-75)
- ``GET  /monitoring/prometheus/metrics`` request counters/latency
  (tf-serving-template.libsonnet:127-130)
"""

from __future__ import annotations

import json
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.observability.metrics import (
    MetricRegistry,
    render_prometheus,
)
from kubeflow_tpu.observability.tracing import (
    REQUEST_ID_HEADER,
    gen_request_id,
    render_debug,
)
from kubeflow_tpu.serving.batcher import DynamicBatcher
from kubeflow_tpu.serving.continuous import PromptTooLong
from kubeflow_tpu.serving.engine import EngineConfig, InferenceEngine
from kubeflow_tpu.serving.qos import QosRejected


class _Metrics:
    """Server-level request metrics on the shared registry: request and
    error counters plus a latency *histogram* (the old renderer exposed a
    sum/count summary — no percentiles, and its own copy of the text
    format)."""

    def __init__(self) -> None:
        self.registry = MetricRegistry()
        self._requests = self.registry.counter(
            "serving_requests_total", "HTTP requests handled")
        self._errors = self.registry.counter(
            "serving_errors_total", "HTTP requests that failed")
        self._latency = self.registry.histogram(
            "serving_latency_seconds", "End-to-end request latency")
        # Cold-start surface (flash-crowd elasticity): where this
        # replica's boot weights came from, and the per-phase birth
        # timing the ≥5x cold-to-first-token gate reads.
        self._weight_pulls = self.registry.counter(
            "serving_weight_pulls_total",
            "Boot weight installs by source (peer = pulled from a "
            "serving donor over :pull; checkpoint = restored from the "
            "store; init = fresh random init)", labels=("source",))
        self._cold_start = self.registry.gauge(
            "serving_cold_start_seconds",
            "Birth phase durations: weights (install), compile "
            "(dispatch-set warm), first_token (boot to serving-ready)",
            labels=("phase",))

    def observe(self, seconds: float, error: bool) -> None:
        self._requests.inc()
        if error:
            self._errors.inc()
        self._latency.observe(seconds)

    def record_weight_pull(self, source: str) -> None:
        self._weight_pulls.labels(source or "init").inc()

    def record_cold_start(self, phases: dict) -> None:
        for phase, seconds in phases.items():
            self._cold_start.labels(phase).set(float(seconds))

    def render(self) -> str:
        return self.registry.render()


class ModelServer:
    """Dual-port model server: REST on ``port`` (:8500 by convention), gRPC
    on ``grpc_port`` (:9000; None disables, 0 binds an ephemeral port for
    tests) — the tf-serving port contract
    (tf-serving-template.libsonnet:43-49). Both ports share one engine and
    one dynamic batcher, so mixed-protocol traffic coalesces into the same
    TPU batches."""

    def __init__(self, engine_cfg: EngineConfig, *, port: int = 8500,
                 grpc_port: int | None = None,
                 batch_timeout_ms: float = 5.0):
        self._t_boot = time.perf_counter()
        self.engine = InferenceEngine(engine_cfg)
        self.batcher = DynamicBatcher(
            self.engine.predict_batch, engine_cfg.batch_size, batch_timeout_ms
        )
        self.metrics = _Metrics()
        self.port = port
        self.grpc_port = grpc_port
        self._httpd: ThreadingHTTPServer | None = None
        self._grpc = None
        # Generation rides the continuous-batching decoder (per-request
        # lengths decoupled, tokens streamable); plain predicts keep the
        # dynamic batcher. Lazily built: non-LM servers never pay for it.
        self._decoder = None
        self._decoder_lock = threading.Lock()
        # Live weight pushes (:weights endpoint): chunk assembly state,
        # serialized so concurrent learner chunks interleave safely.
        self._weights_assembler = None
        self._weights_lock = threading.Lock()
        # Donor-side pull export (:pull endpoint): the flattened host
        # copy of the current epoch's tree, chunk-planned once and
        # re-served to every concurrent newborn; invalidated by version
        # compare when a live push swaps epochs. Leaf lock guarding only
        # the cached tuple (the flatten/pack work runs outside it).
        self._export_cache = None
        self._export_lock = threading.Lock()
        # Readiness ramp: True from construction until warm() covers
        # the boot path — /healthz answers {"status": "warming"} so the
        # gateway route-excludes this replica without failure-counter
        # penalty while it compiles.
        self.warming = True

    @property
    def decoder(self):
        if (self.engine.model.family != "transformer"
                or self.engine.cfg.max_new_tokens <= 0
                or self.engine.cfg.decode_mode != "continuous"):
            return None
        with self._decoder_lock:
            if self._decoder is None:
                from kubeflow_tpu.serving.cold_store import (
                    cold_store_from_ref,
                )
                from kubeflow_tpu.serving.continuous import ContinuousDecoder
                from kubeflow_tpu.serving.kv_directory import KvDirectory
                from kubeflow_tpu.serving.qos import QosPolicy

                qos = (QosPolicy(self.engine.cfg.qos_tenants,
                                 aging_seconds=self.engine.cfg.qos_aging_s)
                       if self.engine.cfg.qos_tenants else None)
                # Fleet KV economy: a sized directory turns the local
                # tiers into fleet-visible ones; the cold ref names the
                # shared content-addressed store (colocated replicas
                # resolving the same mem:// name share one instance).
                # The peer-fetch transport is installed by whichever
                # fleet wraps this server (in-process: DecoderFleet;
                # cross-pod: RemoteActorFleet.fetch_kv against :kv).
                kv_dir = (KvDirectory(self.engine.cfg.kv_directory_size)
                          if self.engine.cfg.kv_directory_size > 0
                          else None)
                self._decoder = ContinuousDecoder(
                    self.engine.params, self.engine.model.config,
                    slots=self.engine.cfg.batch_size,
                    prefill_len=self.engine.cfg.max_seq_len,
                    max_new_tokens=self.engine.cfg.max_new_tokens,
                    top_k=self.engine.cfg.top_k,
                    eos_id=self.engine.cfg.eos_id,
                    chunk_size=self.engine.cfg.decode_chunk,
                    prefix_cache_slots=self.engine.cfg.prefix_cache_slots,
                    prefix_cache_min_len=(
                        self.engine.cfg.prefix_cache_min_len),
                    prefill_len_buckets=self.engine.cfg.prefill_len_buckets,
                    speculative_k=self.engine.cfg.speculative_k,
                    draft_mode=self.engine.cfg.draft_mode,
                    kv_layout=self.engine.cfg.kv_layout,
                    kv_block_size=self.engine.cfg.kv_block_size,
                    kv_pool_blocks=self.engine.cfg.kv_pool_blocks,
                    kv_dtype=self.engine.cfg.kv_dtype,
                    kv_fused=self.engine.cfg.kv_fused,
                    stream_timeout_s=self.engine.cfg.stream_timeout_s,
                    role=self.engine.cfg.serving_role,
                    tp_shards=self.engine.cfg.tp_shards,
                    qos=qos,
                    host_kv_bytes=self.engine.cfg.host_kv_bytes,
                    prefill_chunk_tokens=(
                        self.engine.cfg.prefill_chunk_tokens),
                    max_prompt_len=self.engine.cfg.max_prompt_len,
                    cp_shards=self.engine.cfg.cp_shards,
                    pp_stages=self.engine.cfg.pp_stages,
                    kv_directory=kv_dir,
                    cold_store=cold_store_from_ref(
                        self.engine.cfg.cold_store_ref),
                    kv_import_crossover_tokens=(
                        self.engine.cfg.kv_import_crossover_tokens),
                    replica_name=(
                        f"{self.engine.cfg.model}:{self.port}"),
                    boot_weights_version=self.engine.boot_weights_version,
                    compile_cache_dir=self.engine.cfg.compile_cache_dir,
                )
            return self._decoder

    # ------------------------------------------------------------------

    def handle_predict(self, name: str, body: dict,
                       request_id: str | None = None,
                       qos: dict | None = None) -> dict:
        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        instances = body.get("instances")
        if not isinstance(instances, list) or not instances:
            raise ValueError("body must contain non-empty 'instances'")
        for inst in instances:
            self.engine.validate_instance(inst)
        qos = qos or {}
        # Generation requests go to the continuous decoder (per-request
        # lengths are decoupled — a short request returns as soon as ITS
        # tokens are done); plain predicts coalesce in the dynamic batcher.
        handles = []
        for i, inst in enumerate(instances):
            if inst.get("max_new_tokens") and self.decoder is not None:
                # One HTTP request id; multi-instance bodies suffix the
                # instance index so each stream's timeline stays unique.
                rid = (request_id if request_id and i == 0
                       else f"{request_id}-{i}" if request_id else None)
                handles.append(("gen", inst, self.decoder.submit(
                    inst["tokens"], inst["max_new_tokens"],
                    float(inst.get("temperature", 0.0)),
                    request_id=rid, **qos,
                )))
            else:
                handles.append(("batch", inst,
                                self.batcher.submit_async(inst)))
        preds = []
        for kind, inst, h in handles:
            if kind == "gen":
                preds.append(self._gen_prediction(inst, h.result(
                    with_logits=bool(inst.get("return_logits")) or None,
                )))
            else:
                preds.append(self.batcher.collect(h))
        return {"predictions": preds}

    @staticmethod
    def _gen_prediction(inst: dict, res: dict) -> dict:
        """Shape a decoder result like the lockstep generate path did
        (engine._generate_batch), so clients see one schema either way."""
        import numpy as np

        toks = res["tokens"]
        pred = {
            "next_token": int(toks[0]) if toks
            else int(np.argmax(res["prefill_logits"])),
            "tokens": toks,
            "finish_reason": res["finish_reason"],
        }
        if not toks or inst.get("return_logits"):
            pred["logits"] = res["prefill_logits"].tolist()
        return pred

    def handle_predict_stream(self, name: str, body: dict,
                              request_id: str | None = None,
                              qos: dict | None = None):
        """Streaming generation: yields JSON-line dicts, one per token, then
        a terminal ``{"done": true, ...}`` record. Exactly one instance per
        stream (the chunked-HTTP / gRPC-stream unit is a single sequence)."""
        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        instances = body.get("instances")
        if not isinstance(instances, list) or len(instances) != 1:
            raise ValueError("streaming needs exactly one instance")
        inst = instances[0]
        self.engine.validate_instance(inst)
        if not inst.get("max_new_tokens"):
            raise ValueError("streaming needs 'max_new_tokens' > 0")
        if self.decoder is None:
            raise ValueError("model does not support generation")
        handle = self.decoder.submit(
            inst["tokens"], inst["max_new_tokens"],
            float(inst.get("temperature", 0.0)),
            request_id=request_id, **(qos or {}),
        )

        # Validation above runs eagerly (before the HTTP 200 goes out); only
        # the token iteration is deferred.
        def _records():
            index = 0
            for tok in handle.tokens():
                yield {"token": tok, "index": index}
                index += 1
            res = handle.result()
            yield {
                "done": True,
                "tokens": res["tokens"],
                "finish_reason": res["finish_reason"],
                "ttft_ms": round(1000 * (res["ttft_s"] or 0.0), 3),
            }

        return _records()

    # -- disaggregated prefill/decode handoff --------------------------
    #
    # The HTTP face of ContinuousDecoder.export_prompt/import_prompt:
    # a PREFILL-pool server answers ``:prefill`` by computing the
    # prompt's KV and (when ``handoff_to`` names a decode server)
    # pushing the packed block payload server-to-server at that peer's
    # ``:import`` — the KV bytes never transit the gateway, which only
    # orchestrates the two hops and then relays the ordinary
    # ``:predict`` to the decode server, where it prefix-hits the
    # imported blocks.

    def handle_prefill(self, name: str, body: dict,
                       request_id: str | None = None) -> dict:
        from kubeflow_tpu.serving import handoff as handoff_mod

        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        instances = body.get("instances")
        if not isinstance(instances, list) or len(instances) != 1:
            raise ValueError("prefill handoff needs exactly one instance")
        inst = instances[0]
        self.engine.validate_instance(inst)
        if self.decoder is None:
            raise ValueError("model does not support generation")
        h = self.decoder.export_prompt(inst["tokens"])
        env = handoff_mod.pack(h)
        target = str(body.get("handoff_to", "") or "")
        if target:
            pushed = self._push_handoff(name, target, env, request_id)
            return {"handoff": pushed, "prefix_len": h["prefix_len"]}
        # No destination: hand the envelope back to the caller (tests /
        # out-of-band relays).
        return {"handoff": False, "prefix_len": h["prefix_len"],
                "envelope": env}

    def _push_handoff(self, name: str, target: str, env: dict,
                      request_id: str | None = None) -> bool:
        """POST the packed payload at the decode server's ``:import``.
        Best-effort: any failure returns False — the decode server will
        simply prefill the prompt itself (degraded, never wrong)."""
        host, _, port_s = target.partition(":")
        data = json.dumps(env).encode()
        headers = {"Content-Type": "application/json"}
        if request_id:
            headers[REQUEST_ID_HEADER] = request_id
        try:
            conn = HTTPConnection(host, int(port_s or 80), timeout=30.0)
            try:
                conn.request("POST", f"/v1/models/{name}:import",
                             body=data, headers=headers)
                resp = conn.getresponse()
                out = json.loads(resp.read() or b"{}")
                return resp.status == 200 and bool(out.get("imported"))
            finally:
                conn.close()
        except (OSError, ValueError):
            return False

    def handle_import(self, name: str, body: dict) -> dict:
        from kubeflow_tpu.serving import handoff as handoff_mod

        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        if self.decoder is None:
            raise ValueError("model does not support generation")
        h = handoff_mod.unpack(body)  # ValueError on garbage -> 400
        return {"imported": bool(self.decoder.import_prompt(h))}

    def handle_kv(self, name: str, body: dict) -> dict:
        """The fleet KV economy's pull endpoint (``:kv``): a peer
        replica that saw this server advertised in the prefix directory
        POSTs its prompt here and gets back the deepest cached prefix
        as a packed handoff envelope plus the weights epoch that
        computed it — the requester validates both and refuses stale or
        mismatched envelopes. A prefix this server no longer caches is
        a KeyError (HTTP 404): the hint was stale, the requester
        withdraws it and falls through to the cold tier or a plain
        prefill."""
        from kubeflow_tpu.serving import handoff as handoff_mod

        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        if self.decoder is None:
            raise ValueError("model does not support generation")
        toks = body.get("tokens")
        if not isinstance(toks, list) or not toks:
            raise ValueError("kv pull needs non-empty 'tokens'")
        h = self.decoder.export_prefix(toks)  # KeyError -> 404 on miss
        ver = h.pop("weights_version", 0)
        return {"envelope": handoff_mod.pack(h),
                "weights_version": ver,
                "prefix_len": h["prefix_len"]}

    # -- live weight streaming -----------------------------------------
    #
    # The HTTP face of ContinuousDecoder.update_weights: a learner
    # POSTs chunked weight envelopes (serving/weights.py) directly at
    # each replica's ``:weights`` — server-to-server, the gateway never
    # relays weight bytes. Chunks assemble per weights epoch; the swap
    # installs atomically only when the last chunk lands, so a torn or
    # abandoned push can never reach the decoder.

    def handle_weights(self, name: str, body: dict) -> dict:
        from kubeflow_tpu.serving import weights as weights_mod

        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        decoder = self.decoder
        if decoder is None:
            raise ValueError("model does not support generation")
        chunk = weights_mod.unpack_chunk(body)  # ValueError -> 400
        with self._weights_lock:
            if self._weights_assembler is None:
                self._weights_assembler = weights_mod.WeightChunkAssembler()
            done = self._weights_assembler.add(chunk)
            pending = self._weights_assembler.pending
        if done is None:
            return {"installed": False, "pending": pending,
                    "weights_version": chunk["weights_version"]}
        leaves, has_draft = done
        model_leaves, draft_leaves = weights_mod.split_namespaces(leaves)
        params = weights_mod.unflatten_params(model_leaves,
                                              decoder.params)
        draft = None
        if has_draft:
            spec = getattr(decoder, "_spec", None)
            if spec is None or not hasattr(spec, "params"):
                raise ValueError(
                    "push carries draft weights but no draft-model "
                    "proposer is configured")
            draft = weights_mod.unflatten_params(draft_leaves,
                                                 spec.params)
        installed = decoder.update_weights(
            params, version=chunk["weights_version"], draft_params=draft)
        return {"installed": True, "weights_version": installed}

    def handle_weights_pull(self, name: str, body: dict) -> dict:
        """Donor side of peer weight birth (``:pull``): a NEWBORN
        replica POSTs ``{"seq": k}`` and gets back chunk ``k`` of this
        server's CURRENT weights epoch as a standard push envelope —
        the PR-15 transport's reverse direction, so the newborn's
        weights arrive already at the fleet's version and no checkpoint
        store sits on the scale-up hot path.

        The flattened host tree is chunk-planned once per epoch and
        cached (``_export_cache``); a live push swapping epochs
        mid-pull changes the version the next chunk carries, which the
        puller's assembler treats exactly like a superseded push —
        restart, never a mixed-epoch install. A ``seq`` beyond the
        chunk count is a KeyError (404): the puller overshot a
        shrinking plan after an epoch swap and will restart."""
        from kubeflow_tpu.serving import weights as weights_mod

        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        seq = int(body.get("seq", 0))
        # A decoder (live-pushable) serves its epoch-consistent
        # snapshot; a plain predict server donates the engine's boot
        # tree at the epoch it booted with.
        with self._decoder_lock:
            decoder = self._decoder
        if decoder is not None:
            params, version = decoder.weights_snapshot()
        else:
            params = self.engine.params
            version = self.engine.boot_weights_version
        with self._export_lock:
            cache = self._export_cache
        if cache is None or cache[0] != version:
            # Flatten + plan OUTSIDE the lock (device fetches and a
            # full host copy must not serialize concurrent pulls; a
            # losing racer just rebuilds the same plan).
            items = weights_mod.flatten_namespaced(params)
            groups = weights_mod.plan_chunks(items)
            cache = (version, groups)
            with self._export_lock:
                self._export_cache = cache
        version, groups = cache
        if not 0 <= seq < len(groups):
            raise KeyError(f"weights chunk {seq} beyond plan "
                           f"({len(groups)} chunks at epoch {version})")
        return weights_mod.pack_chunk(groups[seq], version, seq,
                                      len(groups), False)

    def handle_metadata(self, name: str) -> dict:
        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        meta = self.engine.metadata()
        meta["state"] = "AVAILABLE" if self.engine.ready else "LOADING"
        return meta

    # ------------------------------------------------------------------

    def _make_handler(server: "ModelServer"):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload: dict | str,
                      content_type="application/json") -> None:
                body = (
                    payload if isinstance(payload, str)
                    else json.dumps(payload)
                ).encode()
                self.send_response(code)
                rid = getattr(self, "_request_id", None)
                if rid:
                    self.send_header(REQUEST_ID_HEADER, rid)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/healthz", "/livez"):
                    # "warming" is alive-but-not-serving: the gateway
                    # route-excludes without a failure-counter penalty
                    # (a newborn mid-compile is not a dead upstream).
                    status = "warming" if server.warming else "ok"
                    self._send(200, {"status": status})
                elif self.path == "/readyz":
                    code = 200 if server.engine.ready else 503
                    self._send(code, {"ready": server.engine.ready})
                elif self.path == "/monitoring/prometheus/metrics":
                    text = server.metrics.render()
                    if server._decoder is not None:
                        # One renderer for every exporter: the decoder's
                        # registry carries the latency histograms
                        # (TTFT, inter-token, dispatch, queue wait,
                        # occupancy); the dict below maps its counter
                        # snapshot (counters by _total suffix, gauges
                        # otherwise).
                        d = server._decoder.metrics()
                        text += server._decoder.registry.render()
                        text += render_prometheus({
                            "serving_decode_steps_total": d["decode_steps"],
                            "serving_decode_dispatches_total":
                                d["decode_dispatches"],
                            "serving_prefill_dispatches_total":
                                d["prefill_dispatches"],
                            "serving_prefill_tokens_total":
                                d["prefill_tokens"],
                            "serving_requests_admitted_total":
                                d["requests_admitted"],
                            "serving_tokens_emitted_total":
                                d["tokens_emitted"],
                            "serving_ttft_avg_seconds": d["ttft_avg_s"],
                            "serving_prefix_hits_total": d["prefix_hits"],
                            "serving_prefix_misses_total":
                                d["prefix_misses"],
                            "serving_prefix_evictions_total":
                                d["prefix_evictions"],
                            "serving_prefix_tokens_reused_total":
                                d["prefix_tokens_reused"],
                            "serving_prefix_suffix_tokens_total":
                                d["prefix_suffix_tokens"],
                            "serving_prefix_entries": d["prefix_entries"],
                            "serving_spec_drafted_tokens_total":
                                d["spec_drafted_tokens"],
                            "serving_spec_accepted_tokens_total":
                                d["spec_accepted_tokens"],
                            "serving_spec_verify_dispatches_total":
                                d["spec_verify_dispatches"],
                            "serving_spec_draft_dispatches_total":
                                d["spec_draft_dispatches"],
                            "serving_spec_acceptance_rate":
                                d["spec_acceptance_rate"],
                            "serving_kv_blocks_total": d["kv_blocks_total"],
                            "serving_kv_blocks_in_use":
                                d["kv_blocks_in_use"],
                            # Real-byte gauges for the autoscaler:
                            # block counts shift meaning with kv_dtype,
                            # bytes do not.
                            "serving_kv_bytes_per_token":
                                d["kv_bytes_per_token"],
                            "serving_kv_bytes_in_use":
                                d["kv_bytes_in_use"],
                            "serving_kv_bytes_total":
                                d["kv_bytes_total"],
                            "serving_kv_dtype_int8":
                                int(d["kv_dtype"] == "int8"),
                            "serving_kv_cow_copies_total":
                                d["kv_cow_copies"],
                            "serving_kv_shared_blocks_total":
                                d["kv_shared_blocks"],
                            "serving_kv_defer_admissions_total":
                                d["kv_defer_admissions"],
                            # Disaggregated handoff counters (the role
                            # itself rides the serving_role gauge on
                            # the decoder registry above).
                            "serving_kv_handoff_exports_total":
                                d["kv_handoff_exports"],
                            "serving_kv_handoff_imports_total":
                                d["kv_handoff_imports"],
                            "serving_kv_handoff_tokens_total":
                                d["kv_handoff_tokens"],
                            # Tiered KV (HBM -> host) + QoS: tier
                            # occupancy gauges (pinned = suspended
                            # streams' parked payloads) and the
                            # suspend/resume/shed counters.
                            "serving_kv_host_tier_bytes":
                                d["kv_host_tier_bytes"],
                            "serving_kv_host_tier_bytes_total":
                                d["kv_host_tier_bytes_total"],
                            "serving_kv_host_tier_pinned_bytes":
                                d["kv_host_tier_pinned_bytes"],
                            "serving_kv_host_tier_entries":
                                d["kv_host_tier_entries"],
                            "serving_kv_host_demotions_total":
                                d["kv_host_demotions"],
                            "serving_kv_host_promotions_total":
                                d["kv_host_promotions"],
                            "serving_kv_host_evictions_total":
                                d["kv_host_evictions"],
                            # High-water occupancy (sizing signal for
                            # the host tier and the cold store under
                            # it; the eviction-age histogram rides the
                            # decoder registry above).
                            "serving_kv_host_tier_high_water_bytes":
                                d["kv_host_tier_high_water_bytes"],
                            # Fleet KV economy (peer + cold tiers):
                            # hit/miss/bytes per remote tier, the
                            # staleness refusals that prove mid-pull
                            # weight pushes degrade safely, and the
                            # crossover skips (remote KV existed but
                            # the gain was below the import threshold).
                            "serving_kv_peer_hits_total":
                                d["kv_peer_hits"],
                            "serving_kv_peer_misses_total":
                                d["kv_peer_misses"],
                            "serving_kv_peer_import_bytes_total":
                                d["kv_peer_import_bytes"],
                            "serving_kv_peer_fetch_failures_total":
                                d["kv_peer_fetch_failures"],
                            "serving_kv_cold_hits_total":
                                d["kv_cold_hits"],
                            "serving_kv_cold_demotions_total":
                                d["kv_cold_demotions"],
                            "serving_kv_cold_import_bytes_total":
                                d["kv_cold_import_bytes"],
                            "serving_kv_import_stale_refused_total":
                                d["kv_import_stale_refused"],
                            "serving_kv_import_skipped_crossover_total":
                                d["kv_import_skipped_crossover"],
                            "serving_kv_directory_publishes_total":
                                d["kv_directory_publishes"],
                            # Shared-tier gauges, present only when the
                            # replica carries the economy objects.
                            **{f"serving_{k}": d[k] for k in (
                                "kv_cold_store_bytes",
                                "kv_cold_store_bytes_total",
                                "kv_cold_store_entries",
                                "kv_directory_keys") if k in d},
                            "serving_suspends_total": d["kv_suspends"],
                            "serving_resumes_total": d["kv_resumes"],
                            "serving_deadline_shed_total":
                                d["qos_deadline_shed"],
                            "serving_hol_bypasses_total":
                                d["hol_bypasses"],
                            "serving_qos_enabled":
                                int(d["qos_enabled"]),
                            # Live weight streaming: the version gauge,
                            # push counter and push-seconds histogram
                            # ride the decoder registry above; the
                            # stale-hit refusals land here.
                            "serving_weights_stale_refused_total":
                                d["weights_stale_refused"],
                            # Flash-crowd birth surface: persistent
                            # compile-cache coverage of the dispatch
                            # set, and the ramp gate (1 while this
                            # replica is spill-only).
                            "serving_compile_cache_hits_total":
                                d["compile_cache_hits"],
                            "serving_compile_cache_misses_total":
                                d["compile_cache_misses"],
                            "serving_warming": int(d["warming"]),
                            "serving_in_flight": d["in_flight"],
                            "serving_queued": d["queued"],
                            # serving_tp_shards rides the decoder
                            # registry above; the kv_bytes gauges here
                            # are PER CHIP under tp (real per-chip HBM).
                        })
                    self._send(200, text, content_type="text/plain")
                elif self.path.partition("?")[0] == "/debug/requests":
                    # One curl away: the decoder's per-stream lifecycle
                    # timelines (JSON; ?format=chrome for a
                    # chrome://tracing file; ?id=<rid> filters).
                    if server._decoder is None:
                        self._send(200, {"open": [], "finished": []})
                    else:
                        body, ctype = render_debug(
                            server._decoder.trace,
                            self.path.partition("?")[2])
                        self._send(200, body.decode(), content_type=ctype)
                elif self.path.startswith("/v1/models/"):
                    name = self.path[len("/v1/models/"):]
                    try:
                        self._send(200, server.handle_metadata(name))
                    except KeyError as e:
                        self._send(404, {"error": str(e)})
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            # Chunked transfer-encoding requires HTTP/1.1 on the status
            # line — the BaseHTTPRequestHandler default is HTTP/1.0, under
            # which spec-compliant clients would read the chunk framing as
            # payload.
            protocol_version = "HTTP/1.1"

            def _chunk(self, rec: dict) -> None:
                data = (json.dumps(rec) + "\n").encode()
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")
                self.wfile.flush()

            def _send_stream(self, records) -> None:
                """Chunked transfer-encoding, one JSON line per record —
                each token flushes to the client as it is sampled (the
                gateway's streamed proxying passes chunks through). Once
                the 200 goes out this owns the connection: a mid-stream
                decoder failure becomes an error record + clean terminal
                chunk, never a second status line."""
                self.send_response(200)
                rid = getattr(self, "_request_id", None)
                if rid:
                    self.send_header(REQUEST_ID_HEADER, rid)
                self.send_header("Content-Type", "application/jsonlines")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    for rec in records:
                        self._chunk(rec)
                except Exception as e:
                    self._chunk({"error": str(e), "done": True})
                finally:
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()

            def _qos_headers(self) -> dict:
                """The QoS surface threaded from the gateway: tenant
                identity, request priority, and a shed deadline. Bad
                numeric values are a client error (400 via the
                ValueError path), not a silent default."""
                qos = {}
                tenant = self.headers.get("X-Tenant")
                if tenant:
                    qos["tenant"] = tenant
                prio = self.headers.get("X-Priority")
                if prio:
                    try:
                        qos["priority"] = int(prio)
                    except ValueError:
                        raise ValueError(
                            f"malformed X-Priority {prio!r}") from None
                deadline = self.headers.get("X-Deadline-Ms")
                if deadline:
                    try:
                        qos["deadline_ms"] = float(deadline)
                    except ValueError:
                        raise ValueError(
                            f"malformed X-Deadline-Ms {deadline!r}"
                        ) from None
                return qos

            def do_POST(self):
                t0 = time.perf_counter()
                error = False
                # Request id: honor the gateway's (or the client's),
                # mint one otherwise; echoed on every response and keyed
                # into the decoder's timeline for this stream.
                self._request_id = (self.headers.get(REQUEST_ID_HEADER)
                                    or gen_request_id())
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    if self.path.startswith("/v1/models/") and \
                            self.path.endswith(":predict"):
                        name = self.path[len("/v1/models/"):-len(":predict")]
                        qos = self._qos_headers()
                        if body.get("stream"):
                            self._send_stream(
                                server.handle_predict_stream(
                                    name, body,
                                    request_id=self._request_id,
                                    qos=qos)
                            )
                        else:
                            self._send(200, server.handle_predict(
                                name, body,
                                request_id=self._request_id, qos=qos))
                    elif self.path.startswith("/v1/models/") and \
                            self.path.endswith(":prefill"):
                        name = self.path[len("/v1/models/"):-len(":prefill")]
                        self._send(200, server.handle_prefill(
                            name, body, request_id=self._request_id))
                    elif self.path.startswith("/v1/models/") and \
                            self.path.endswith(":import"):
                        name = self.path[len("/v1/models/"):-len(":import")]
                        self._send(200, server.handle_import(name, body))
                    elif self.path.startswith("/v1/models/") and \
                            self.path.endswith(":kv"):
                        name = self.path[len("/v1/models/"):-len(":kv")]
                        self._send(200, server.handle_kv(name, body))
                    elif self.path.startswith("/v1/models/") and \
                            self.path.endswith(":weights"):
                        name = self.path[len("/v1/models/"):
                                         -len(":weights")]
                        self._send(200, server.handle_weights(name, body))
                    elif self.path.startswith("/v1/models/") and \
                            self.path.endswith(":pull"):
                        name = self.path[len("/v1/models/"):-len(":pull")]
                        self._send(200,
                                   server.handle_weights_pull(name, body))
                    else:
                        error = True
                        self._send(404, {"error": f"no route {self.path}"})
                except KeyError as e:
                    error = True
                    self._send(404, {"error": str(e)})
                except QosRejected as e:
                    # Token-bucket overload: shed with backpressure the
                    # client can act on instead of queuing into
                    # collapse.
                    error = True
                    self.send_response(429)
                    rid = getattr(self, "_request_id", None)
                    if rid:
                        self.send_header(REQUEST_ID_HEADER, rid)
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_header("Retry-After",
                                     str(max(1, int(e.retry_after_s
                                                    + 0.999))))
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except TimeoutError as e:
                    # An overloaded/stalled decoder is a server-side
                    # failure, not a bad request (deadline sheds — a
                    # DeadlineExceeded is a TimeoutError — land here
                    # too: the answer's window has passed).
                    error = True
                    self._send(503, {"error": str(e) or "generation "
                                     "timed out"})
                except PromptTooLong as e:
                    # Terminal size rejection (prompt beyond the
                    # replica's ceiling even chunked) — 413 so clients
                    # can tell "shrink the prompt" from 400's "fix the
                    # request" and from memory-pressure 503s. Ordered
                    # before ValueError: PromptTooLong subclasses it.
                    error = True
                    self._send(413, {"error": str(e)})
                except ValueError as e:
                    error = True
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    error = True
                    self._send(500, {"error": str(e)})
                finally:
                    server.metrics.observe(time.perf_counter() - t0, error)

        return Handler

    def _start_grpc(self) -> None:
        if self.grpc_port is None:
            return
        from kubeflow_tpu.serving.grpc_server import GrpcPredictionService

        self._grpc = GrpcPredictionService(self, port=self.grpc_port)
        self.grpc_port = self._grpc.bound_port  # resolve port 0 → real port
        self._grpc.start()

    def warm(self) -> None:
        """Boot warm path, run AFTER the HTTP port binds so ``/healthz``
        answers ``warming`` (route-excluded, not dead) for the whole
        birth instead of connection-refusing: engine warmup (compiles
        the predict executable), then — when the flash-crowd surface is
        configured (``compile_cache_dir``/``weight_peers``) — an eager
        decoder build + dispatch-set warm so the replica joins the
        fleet with nothing left to compile. Publishes the per-phase
        cold-start breakdown and flips ``warming`` off."""
        t0 = time.perf_counter()
        self.engine.warmup()
        if self.engine.cfg.compile_cache_dir or self.engine.cfg.weight_peers:
            decoder = self.decoder
            if decoder is not None:
                decoder.warming = True
                decoder.warm()
        self.engine.cold_start["compile"] = time.perf_counter() - t0
        self.engine.cold_start["first_token"] = (time.perf_counter()
                                                 - self._t_boot)
        self.metrics.record_cold_start(self.engine.cold_start)
        self.metrics.record_weight_pull(self.engine.weight_pull_source)
        self.warming = False

    def start(self) -> None:
        self._start_grpc()
        self._httpd = ThreadingHTTPServer(
            ("0.0.0.0", self.port), self._make_handler()
        )
        self.port = self._httpd.server_address[1]
        thread = threading.Thread(target=self._httpd.serve_forever,
                                  daemon=True)
        thread.start()
        self.warm()

    def serve_forever(self) -> None:
        self._start_grpc()
        self._httpd = ThreadingHTTPServer(
            ("0.0.0.0", self.port), self._make_handler()
        )
        # Warm on a side thread: the accept loop must answer health
        # probes (as "warming") while the dispatch set compiles.
        threading.Thread(target=self.warm, daemon=True).start()
        self._httpd.serve_forever()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
        if self._grpc is not None:
            self._grpc.stop()
        self.batcher.stop()
        with self._decoder_lock:
            if self._decoder is not None:
                self._decoder.stop()
