"""REST model server.

The http-proxy surface (components/k8s-model-server/http-proxy/server.py:
PredictHandler :251, metadata :154) served directly from the TPU process:

- ``POST /v1/models/<name>:predict``  {"instances": [...]} → {"predictions": [...]}
- ``GET  /v1/models/<name>``          model metadata + availability
- ``GET  /healthz`` ``GET /readyz``   liveness/readiness (probe target,
  tf-serving-template.libsonnet:70-75)
- ``GET  /monitoring/prometheus/metrics`` request counters/latency
  (tf-serving-template.libsonnet:127-130)
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.serving.batcher import DynamicBatcher
from kubeflow_tpu.serving.engine import EngineConfig, InferenceEngine


class _Metrics:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.requests = 0
        self.errors = 0
        self.latency_sum = 0.0
        self.latency_count = 0

    def observe(self, seconds: float, error: bool) -> None:
        with self.lock:
            self.requests += 1
            self.errors += int(error)
            self.latency_sum += seconds
            self.latency_count += 1

    def render(self) -> str:
        with self.lock:
            return (
                "# TYPE serving_requests_total counter\n"
                f"serving_requests_total {self.requests}\n"
                "# TYPE serving_errors_total counter\n"
                f"serving_errors_total {self.errors}\n"
                "# TYPE serving_latency_seconds summary\n"
                f"serving_latency_seconds_sum {self.latency_sum:.6f}\n"
                f"serving_latency_seconds_count {self.latency_count}\n"
            )


class ModelServer:
    """Dual-port model server: REST on ``port`` (:8500 by convention), gRPC
    on ``grpc_port`` (:9000; None disables, 0 binds an ephemeral port for
    tests) — the tf-serving port contract
    (tf-serving-template.libsonnet:43-49). Both ports share one engine and
    one dynamic batcher, so mixed-protocol traffic coalesces into the same
    TPU batches."""

    def __init__(self, engine_cfg: EngineConfig, *, port: int = 8500,
                 grpc_port: int | None = None,
                 batch_timeout_ms: float = 5.0):
        self.engine = InferenceEngine(engine_cfg)
        self.batcher = DynamicBatcher(
            self.engine.predict_batch, engine_cfg.batch_size, batch_timeout_ms
        )
        self.metrics = _Metrics()
        self.port = port
        self.grpc_port = grpc_port
        self._httpd: ThreadingHTTPServer | None = None
        self._grpc = None

    # ------------------------------------------------------------------

    def handle_predict(self, name: str, body: dict) -> dict:
        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        instances = body.get("instances")
        if not isinstance(instances, list) or not instances:
            raise ValueError("body must contain non-empty 'instances'")
        for inst in instances:
            self.engine.validate_instance(inst)
        # Enqueue every instance first so the batcher can coalesce a
        # multi-instance request into full batches, then collect.
        pending = [self.batcher.submit_async(inst) for inst in instances]
        preds = [self.batcher.collect(p) for p in pending]
        return {"predictions": preds}

    def handle_metadata(self, name: str) -> dict:
        if name != self.engine.cfg.model:
            raise KeyError(f"model {name!r} not served")
        meta = self.engine.metadata()
        meta["state"] = "AVAILABLE" if self.engine.ready else "LOADING"
        return meta

    # ------------------------------------------------------------------

    def _make_handler(server: "ModelServer"):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, payload: dict | str,
                      content_type="application/json") -> None:
                body = (
                    payload if isinstance(payload, str)
                    else json.dumps(payload)
                ).encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/healthz", "/livez"):
                    self._send(200, {"status": "ok"})
                elif self.path == "/readyz":
                    code = 200 if server.engine.ready else 503
                    self._send(code, {"ready": server.engine.ready})
                elif self.path == "/monitoring/prometheus/metrics":
                    self._send(200, server.metrics.render(),
                               content_type="text/plain")
                elif self.path.startswith("/v1/models/"):
                    name = self.path[len("/v1/models/"):]
                    try:
                        self._send(200, server.handle_metadata(name))
                    except KeyError as e:
                        self._send(404, {"error": str(e)})
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                t0 = time.perf_counter()
                error = False
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    if self.path.startswith("/v1/models/") and \
                            self.path.endswith(":predict"):
                        name = self.path[len("/v1/models/"):-len(":predict")]
                        self._send(200, server.handle_predict(name, body))
                    else:
                        error = True
                        self._send(404, {"error": f"no route {self.path}"})
                except KeyError as e:
                    error = True
                    self._send(404, {"error": str(e)})
                except (ValueError, TimeoutError) as e:
                    error = True
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    error = True
                    self._send(500, {"error": str(e)})
                finally:
                    server.metrics.observe(time.perf_counter() - t0, error)

        return Handler

    def _start_grpc(self) -> None:
        if self.grpc_port is None:
            return
        from kubeflow_tpu.serving.grpc_server import GrpcPredictionService

        self._grpc = GrpcPredictionService(self, port=self.grpc_port)
        self.grpc_port = self._grpc.bound_port  # resolve port 0 → real port
        self._grpc.start()

    def start(self) -> None:
        self.engine.warmup()
        self._start_grpc()
        self._httpd = ThreadingHTTPServer(
            ("0.0.0.0", self.port), self._make_handler()
        )
        self.port = self._httpd.server_address[1]
        thread = threading.Thread(target=self._httpd.serve_forever,
                                  daemon=True)
        thread.start()

    def serve_forever(self) -> None:
        self.engine.warmup()
        self._start_grpc()
        self._httpd = ThreadingHTTPServer(
            ("0.0.0.0", self.port), self._make_handler()
        )
        self._httpd.serve_forever()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
        if self._grpc is not None:
            self._grpc.stop()
        self.batcher.stop()
