"""Inference engine: registry model + checkpoint → jitted predict.

TPU-first: one compiled function per (padded) batch shape, inputs padded to
the fixed server batch so every request rides the same executable; bf16
activations; optional greedy decode loop for LMs via ``lax.scan`` (static
length, compiled once).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.registry import ModelSpec, get_model


def pow2_bucket(n: int, cap: int | None = None) -> int:
    """Smallest power of two >= ``n`` (floored at 1), clamped to ``cap``.

    The shared shape-bucketing rule: the continuous decoder buckets BOTH
    its admission batch size and (with ``prefill_len_buckets``) the
    prefill sequence length through this, so the number of compiled
    prefill executables stays logarithmic in each dimension.
    """
    bucket = 1
    while bucket < n:
        bucket *= 2
    if cap is not None:
        bucket = min(bucket, cap)
    return bucket


@dataclass
class EngineConfig:
    model: str = "lm-test-tiny"
    checkpoint_dir: str | None = None
    batch_size: int = 8
    max_seq_len: int = 128
    # Autoregressive decode surface (transformer family): fixed compiled
    # decode length (instances request up to this many), optional top-k.
    max_new_tokens: int = 16
    top_k: int = 0
    # Sampling this id ends a generation early (frees the decode slot);
    # None disables (the synthetic test models have no EOS convention).
    eos_id: int | None = None
    # "continuous": per-request lengths decoupled, streamable (default).
    # "lockstep": one compiled prefill+decode per batch — fewer dispatches,
    # the right mode when host↔device RTT dominates (remote TPU tunnels)
    # or for offline batch predict.
    decode_mode: str = "continuous"
    # Decode steps fused into one device dispatch in continuous mode
    # (models/decode.py:decode_chunk). 1 = per-token dispatch (finest
    # streaming/admission granularity; right for local TPU). K>1 pays
    # K× fewer host↔device round-trips at up-to-K-step admission delay —
    # set ~max_new_tokens on high-RTT links (measured on the dev tunnel:
    # chunk 31 → 1.79× lockstep full-gen p50 vs chunk 8's 2.6×,
    # BASELINE.md round 4) while keeping per-request decoupling.
    decode_chunk: int = 1
    # Device-resident prefix KV cache (continuous mode): pool slots for
    # cached prompt prefixes (0 disables). A matching admission gathers
    # the cached K/V rows and prefills ONLY its suffix; finished prompts
    # publish their prefix back to the pool (LRU eviction, in-flight
    # pins). Memory per slot: 2 * layers * max_seq_len * kv_heads *
    # head_dim * dtype bytes.
    prefix_cache_slots: int = 0
    # Shortest prefix worth caching or matching: below this the reuse
    # bookkeeping costs more than the prefill it saves.
    prefix_cache_min_len: int = 16
    # Speculative decoding (continuous mode): number of draft tokens
    # verified per fused dispatch (0 disables). A verify scores K cheap
    # proposals in one [slots, K] forward and keeps each row's longest
    # accepted prefix plus one committed token — up to K+1 tokens per
    # decode round-trip. Greedy outputs are byte-identical either way;
    # temperature>0 rows rejection-resample (distribution unchanged).
    speculative_k: int = 0
    # Where drafts come from: "ngram" (host-side prompt/output n-gram
    # lookup, zero device cost) or "model:<registry-name>" (a small
    # draft model sharing the slot layout).
    draft_mode: str = "ngram"
    # Power-of-two sequence-length buckets for prefill: the number of
    # bucket steps below max_seq_len (0 = pad every prompt to
    # max_seq_len). E.g. 3 with max_seq_len=128 allows prefill shapes
    # {16, 32, 64, 128}, so a 6-token prompt rides a 16-wide executable
    # instead of paying full-length prefill compute.
    prefill_len_buckets: int = 0
    # KV-cache layout (continuous mode). "dense": one
    # [total_len = max_seq_len + max_new_tokens] K/V row reserved per
    # decode slot — worst-case HBM per admission. "paged": K/V lives in
    # a pool of kv_block_size-token blocks mapped through per-slot block
    # tables, so a request only holds blocks for its OWN prompt+budget,
    # admission is bounded by memory (tokens resident) instead of slots,
    # and prefix-cache hits share blocks by refcount with zero device
    # copies. Greedy outputs are byte-identical between layouts.
    kv_layout: str = "dense"
    # Tokens per KV block (paged). Must divide max_seq_len +
    # max_new_tokens. Smaller blocks waste less tail (internal
    # fragmentation ~ block_size/2 tokens per request) but lengthen the
    # block table; 16 suits the default shapes.
    kv_block_size: int = 16
    # Physical blocks in the paged pool. 0 = dense-parity sizing
    # (batch_size * total_len / kv_block_size): same worst case as
    # dense. Set explicitly to cap KV HBM — admission then defers
    # instead of overcommitting.
    kv_pool_blocks: int = 0
    # KV residency precision (paged layout). "fp" keeps the model dtype
    # — greedy outputs bitwise-identical to dense, the pinned-accuracy
    # default. "int8" quantizes blocks (per-position per-head abs-max
    # scales, dequantized at read): ~2x blocks per HBM byte at a pinned
    # greedy-token tolerance; size kv_pool_blocks up accordingly.
    kv_dtype: str = "fp"
    # Fused block-table attention for the paged decode step: walk the
    # table inside the attention kernel (int8 dequantized in-register)
    # instead of gathering the dense [slots, total_len] KV view every
    # step. Off by default — the gather path is the bitwise-parity
    # reference; fused numerics are f32-equivalent, not bitwise.
    kv_fused: bool = False
    # Default wait (seconds) for StreamHandle.tokens()/result() when the
    # caller passes none — raise it when memory-deferred admissions
    # under load would spuriously time callers out.
    stream_timeout_s: float = 60.0
    # Disaggregated-fleet role: "" (colocated), "prefill" (prompt
    # admission only — decode peers pull finished prompt KV via the
    # :prefill/:import handoff endpoints) or "decode" (resumes imported
    # prompts). Requires kv_layout="paged"; surfaces as the
    # `serving_role` exposition label so per-pool dashboards and the
    # operator scrape can tell the pools apart.
    serving_role: str = ""
    # Tensor-parallel shards per replica (continuous mode): >1 runs the
    # decoder over a tp-wide tensor mesh — weights Megatron-split by the
    # model's partition rules, the KV pool sharded over the KV-head
    # axis (block ids stay host-global, so the prefix trie, allocator
    # refcount/CoW, and the prefill→decode handoff are unchanged). Must
    # divide the model's n_kv_heads / n_heads / d_ff; the serving pod
    # needs tp chips. The `serving_kv_bytes_*` gauges then price the
    # pool PER CHIP.
    tp_shards: int = 1
    # Long-context serving (continuous mode, paged layout). Chunked
    # prefill: >0 admits any prompt whose (post-prefix-hit) suffix
    # exceeds this as a CHAIN of bounded chunk dispatches interleaved
    # with decode rounds — the chunk width bounds the worst-case gap a
    # long admission inserts into live streams' inter-token cadence.
    # Token streams stay byte-identical to monolithic prefill. Must be
    # <= max_seq_len; 0 disables (monolithic admission, pre-chunking
    # behavior).
    prefill_chunk_tokens: int = 0
    # Prompt-length ceiling. 0 = max_seq_len (the compiled prefill
    # width). Raising it past max_seq_len requires
    # prefill_chunk_tokens > 0: chunks ride the paged block scatter, so
    # only the virtual KV row — not any compiled shape — bounds the
    # prompt. Prompts beyond the ceiling are rejected with HTTP 413
    # (never silently truncated). Sizes the KV row: total = this +
    # max_new_tokens; kv_block_size must divide it.
    max_prompt_len: int = 0
    # Context-parallel shards (continuous mode): >1 adds a `sequence`
    # mesh axis and runs each prefill chunk's attention ring-style
    # across it (parallel/ring_attention.py collective-permute core
    # over the gathered paged span) — prefill FLOPs/bandwidth for long
    # prompts scale with cp while decode stays tp-only. Requires
    # prefill_chunk_tokens > 0 and the paged gather path (not
    # kv_fused); pow2; the pod needs tp*cp*pp chips.
    cp_shards: int = 1
    # Pipeline-parallel decoder stages: >1 shards the stacked layer
    # weights AND the KV pool's leading layer dim over the outermost
    # `pipeline` mesh axis — per-chip weight and KV bytes divide by pp
    # (long contexts fit where a tp-only replica OOMs) while block ids
    # stay host-global (allocator/trie/handoff unchanged). Must divide
    # the model's n_layers; the pod needs tp*cp*pp chips.
    pp_stages: int = 1
    # Host-RAM KV tier budget in bytes (paged layout; 0 disables).
    # Prefix-trie evictions DEMOTE their blocks here instead of freeing
    # outright, trie misses probe it before cold prefill (second-chance
    # cache — effective pool size rises past HBM at equal device
    # bytes), and QoS suspensions park live streams' KV here until
    # resume.
    host_kv_bytes: int = 0
    # Fleet KV economy: distinct affinity keys the prefix→holder
    # directory tracks (paged layout; 0 disables the economy — the
    # tiers above stay replica-private). With a directory, the miss
    # path runs trie → host → peer → cold → prefill: local misses
    # probe directory hints, pull the deepest advertised prefix from
    # the holding peer over the PR-9 handoff envelope (:kv endpoint),
    # and prefill only the tail.
    kv_directory_size: int = 0
    # Shared cold content-addressed store ref ("mem://<name>[?bytes=n]";
    # empty disables). Host-tier evictions demote their payload here
    # before dropping bytes; the weights epoch rides the content key,
    # so a live weight push invalidates every pre-swap blob by
    # construction.
    cold_store_ref: str = ""
    # Recompute-vs-import crossover: minimum prefill tokens a remote
    # (peer/cold) import must save over the best LOCAL tier before the
    # pull is worth its fixed cost (RTT + envelope codec + scatter).
    # 0 = import any strictly deeper match.
    kv_import_crossover_tokens: int = 0
    # Multi-tenant QoS tenants: "name=weight[:rate[:burst[:priority]]]"
    # comma-separated (serving/qos.py:parse_tenants). Empty disables
    # QoS entirely — FIFO admission, one implicit tenant, exactly the
    # pre-QoS decoder. With tenants set, submits carry
    # tenant/priority/deadline (gateway X-Tenant/X-Priority/
    # X-Deadline-Ms headers), token buckets 429 over-rate tenants, and
    # the pop loop orders by weighted fair share + aged priority.
    qos_tenants: str = ""
    # Seconds of queue wait worth one priority point (starvation
    # aging); <= 0 disables aging.
    qos_aging_s: float = 30.0
    # Flash-crowd elasticity: peer weight birth. Comma-separated donor
    # addresses ("host:port,host:port" — serving peers of the same
    # model). When set, boot pulls the param pytree from the first
    # answering donor over the chunked :pull envelope instead of
    # touching the checkpoint store — the weights arrive already at the
    # fleet's live epoch, so a newborn joining mid-rollout is
    # version-consistent by construction. Donors are tried in order; a
    # donor dying mid-stream falls through to the next, and an empty
    # chain falls back to checkpoint_dir (a newborn always comes up).
    weight_peers: str = ""
    # Per-donor transport timeout for the birth pull.
    weight_pull_timeout_s: float = 30.0
    # Persistent compile cache directory (shared volume across a pool's
    # replicas; empty disables). The server pre-warms the decode
    # dispatch set at start, pointed at this directory — see
    # serving/compile_cache.py for the fingerprint/invalidation scheme.
    compile_cache_dir: str = ""
    # Compute dtype override ("bfloat16"/"float32"); empty keeps the
    # model preset's dtype. The tpu-serving manifest's --dtype arg.
    dtype: str = ""


def _predict_impl(model: ModelSpec, params, inputs):
    cfg = model.config
    if model.family == "transformer":
        logits = model.apply(params, inputs["tokens"], cfg)
        # Causality makes position len-1 exact regardless of padding
        # after it — gather each request's last real position.
        last = jnp.take_along_axis(
            logits, inputs["last_index"][:, None, None], axis=1
        )[:, 0]
        return {
            "logits": last.astype(jnp.float32),
            "next_token": jnp.argmax(last, axis=-1),
        }
    if model.family == "bert":
        seq, pooled = model.apply(
            params, inputs["tokens"], cfg,
            pad_mask=inputs.get("pad_mask"),
        )
        return {"pooled": pooled.astype(jnp.float32)}
    if model.family == "resnet":
        logits = model.apply(params, inputs["images"], cfg)
        return {
            "probabilities": jax.nn.softmax(logits, axis=-1),
            "classes": jnp.argmax(logits, axis=-1),
        }
    raise ValueError(model.family)


# One jitted predict wrapper per (model, dtype): jax.jit over a bound
# method is a fresh wrapper — and a fresh executable — per engine
# instance, so a flash-crowd newborn in the same process would re-pay
# the lockstep predict compile its donor already paid. Sharing the
# wrapper makes the whole dispatch surface executable-cached the way
# the decoder's module-level jits already are; across processes the
# persistent XLA cache (compile_cache.configure_jax_cache) covers it.
_PREDICT_JIT: dict[tuple[str, str], object] = {}


class InferenceEngine:
    """Thread-safe predict over a fixed-shape compiled function."""

    def __init__(self, cfg: EngineConfig):
        self.cfg = cfg
        overrides = {"dtype": jnp.dtype(cfg.dtype)} if cfg.dtype else {}
        self.model: ModelSpec = get_model(cfg.model, **overrides)
        self._lock = threading.Lock()
        # Replica-birth accounting: where the boot weights came from
        # ("peer" / "checkpoint" / "init"), the donor's weights epoch
        # (0 = boot weights, checkpoint semantics), and the per-phase
        # cold-start seconds the server publishes as
        # serving_cold_start_seconds{phase}.
        self.weight_pull_source = "init"
        self.boot_weights_version = 0
        self.cold_start: dict[str, float] = {}
        import time as _time

        t0 = _time.perf_counter()
        self.params = self._load_params()
        self.cold_start["weights"] = _time.perf_counter() - t0
        jit_key = (cfg.model, cfg.dtype or "")
        if jit_key not in _PREDICT_JIT:
            import functools

            _PREDICT_JIT[jit_key] = jax.jit(
                functools.partial(_predict_impl, self.model))
        self._predict = _PREDICT_JIT[jit_key]
        self._seed = 0
        self._warm = False

    def _pull_params_from_peers(self):
        """Peer weight birth: try each configured donor in order over
        the chunked ``:pull`` envelope. Returns the assembled params
        (stamping source/epoch) or None when every donor is dead — the
        caller then falls back to the checkpoint path, so a newborn
        always comes up."""
        from kubeflow_tpu.serving import weights as weights_mod

        reference = self.model.init(jax.random.PRNGKey(0),
                                    self.model.config)
        for donor in [p.strip() for p in self.cfg.weight_peers.split(",")
                      if p.strip()]:
            try:
                leaves, version, _has_draft = weights_mod.pull_weights(
                    donor, self.cfg.model,
                    timeout=self.cfg.weight_pull_timeout_s)
                model_leaves, _ = weights_mod.split_namespaces(leaves)
                params = weights_mod.unflatten_params(model_leaves,
                                                      reference)
            except (OSError, ValueError) as e:
                # Dead / mid-stream-dying / misbehaving donor: the
                # assembler guarantees nothing partial survived; move
                # to the next donor.
                import logging

                logging.getLogger(__name__).warning(
                    "weight pull from donor %s failed: %s", donor, e)
                continue
            self.weight_pull_source = "peer"
            self.boot_weights_version = int(version)
            return params
        return None

    @staticmethod
    def _normalize_placement(params):
        """Land the boot weights as uncommitted default-device arrays —
        the same flavor ``update_weights`` installs — regardless of
        birth path. The jit executable cache keys on array sharding as
        well as avals: a checkpoint restore hands back COMMITTED
        arrays while a peer pull hands back host numpy, and without
        this normalization a newborn recompiles executables its donor
        (or the persistent compile cache) already holds."""
        return jax.device_put(jax.tree.map(np.asarray, params))

    def _load_params(self):
        if self.cfg.weight_peers:
            params = self._pull_params_from_peers()
            if params is not None:
                return self._normalize_placement(params)
        params = self.model.init(jax.random.PRNGKey(0), self.model.config)
        if self.cfg.checkpoint_dir:
            from kubeflow_tpu.train import checkpoint as ckpt_lib
            from kubeflow_tpu.train.optimizers import OptimizerConfig
            from kubeflow_tpu.train.trainer import init_state

            state = init_state(
                jax.random.PRNGKey(0), self.model, OptimizerConfig()
            )
            abstract = jax.eval_shape(lambda: state)
            restored = ckpt_lib.restore_latest(self.cfg.checkpoint_dir,
                                               abstract)
            if restored is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self.cfg.checkpoint_dir}"
                )
            params = restored[0].params
            self.weight_pull_source = "checkpoint"
        return self._normalize_placement(params)

    # ------------------------------------------------------------------

    def _predict_fn(self, params, inputs):
        return _predict_impl(self.model, params, inputs)

    def warmup(self) -> None:
        self.predict_batch(self._example_instances(1))
        self._warm = True

    @property
    def ready(self) -> bool:
        return self._warm

    def validate_instance(self, inst: dict) -> None:
        """Reject malformed instances before they reach a batch (an empty
        'tokens' list would wrap last_index to -1 and return garbage logits
        with 200 OK)."""
        if not isinstance(inst, dict):
            raise ValueError("each instance must be an object")
        if self.model.family in ("transformer", "bert"):
            toks = inst.get("tokens")
            if not isinstance(toks, list) or not toks:
                raise ValueError(
                    "each instance needs a non-empty 'tokens' list"
                )
            if not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in toks):
                raise ValueError("'tokens' must be a flat list of ints")
            want = inst.get("max_new_tokens", 0)
            if not isinstance(want, int) or want < 0:
                raise ValueError("'max_new_tokens' must be a non-negative int")
            if want > self.cfg.max_new_tokens:
                raise ValueError(
                    f"'max_new_tokens' {want} exceeds server limit "
                    f"{self.cfg.max_new_tokens}"
                )
            temp = inst.get("temperature", 0.0)
            if not isinstance(temp, (int, float)) or temp < 0:
                raise ValueError("'temperature' must be a non-negative number")
        elif self.model.family == "resnet":
            if "images" not in inst:
                raise ValueError("each instance needs 'images'")
            cfg = self.model.config
            try:
                arr = np.asarray(inst["images"], np.float32)
            except (TypeError, ValueError) as e:
                raise ValueError(f"'images' not numeric: {e}") from None
            want = (cfg.image_size, cfg.image_size, 3)
            if arr.shape != want:
                raise ValueError(
                    f"'images' shape {arr.shape} != expected {want}"
                )

    def _example_instances(self, n: int) -> list[dict]:
        cfg = self.model.config
        if self.model.family in ("transformer", "bert"):
            return [{"tokens": [0] * 8}] * n
        return [{"images": np.zeros(
            (cfg.image_size, cfg.image_size, 3)).tolist()}] * n

    # ------------------------------------------------------------------

    def _pad_tokens(self, instances: list[dict]) -> dict:
        b = self.cfg.batch_size
        t = self.cfg.max_seq_len
        tokens = np.zeros((b, t), np.int32)
        mask = np.zeros((b, t), np.float32)
        for i, inst in enumerate(instances):
            seq = np.asarray(inst["tokens"], np.int32)[:t]
            tokens[i, : len(seq)] = seq
            mask[i, : len(seq)] = 1.0
        return {"tokens": tokens, "pad_mask": mask}

    def _generate_batch(self, instances: list[dict]) -> list[dict]:
        """Autoregressive path: prefill + KV-cache decode in one compiled
        call; per-row temperature, per-row requested length sliced out."""
        from kubeflow_tpu.models.decode import generate

        n = len(instances)
        b, t = self.cfg.batch_size, self.cfg.max_seq_len
        tokens = np.zeros((b, t), np.int32)
        lengths = np.ones((b,), np.int32)
        temperature = np.zeros((b,), np.float32)
        for i, inst in enumerate(instances):
            seq = np.asarray(inst["tokens"], np.int32)[:t]
            tokens[i, : len(seq)] = seq
            lengths[i] = len(seq)
            temperature[i] = float(inst.get("temperature", 0.0))
        row_valid = np.zeros((b,), bool)
        row_valid[:n] = True
        with self._lock:
            self._seed += 1
            toks, last = generate(
                self.params, jnp.asarray(tokens), jnp.asarray(lengths),
                self.model.config,
                max_new_tokens=self.cfg.max_new_tokens,
                key=jax.random.PRNGKey(self._seed),
                temperature=jnp.asarray(temperature),
                top_k=self.cfg.top_k,
                row_valid=jnp.asarray(row_valid),
            )
        toks = np.asarray(toks)[:n]
        last = np.asarray(last)[:n]
        out = []
        for i, inst in enumerate(instances):
            want = min(int(inst.get("max_new_tokens", 0)),
                       self.cfg.max_new_tokens)
            pred = {
                "next_token": int(toks[i, 0]) if want else
                int(np.argmax(last[i])),
                "tokens": toks[i, :want].tolist(),
            }
            # Full-vocab logits are huge as JSON (32k floats/row); include
            # them only for plain predicts or on explicit request.
            if not want or inst.get("return_logits"):
                pred["logits"] = last[i].tolist()
            out.append(pred)
        return out

    def predict_batch(self, instances: list[dict]) -> list[dict]:
        """Pad instances to the server batch, run, slice real results."""
        if len(instances) > self.cfg.batch_size:
            raise ValueError(
                f"batch {len(instances)} exceeds limit {self.cfg.batch_size}"
            )
        n = len(instances)
        if (self.model.family == "transformer"
                and any(inst.get("max_new_tokens") for inst in instances)):
            return self._generate_batch(instances)
        if self.model.family in ("transformer", "bert"):
            batch = self._pad_tokens(instances)
            if self.model.family == "transformer":
                batch.pop("pad_mask")
                lengths = [
                    min(len(inst["tokens"]), self.cfg.max_seq_len)
                    for inst in instances
                ] + [1] * (self.cfg.batch_size - n)
                batch["last_index"] = np.asarray(lengths, np.int32) - 1
        else:
            cfg = self.model.config
            images = np.zeros(
                (self.cfg.batch_size, cfg.image_size, cfg.image_size, 3),
                np.float32,
            )
            for i, inst in enumerate(instances):
                images[i] = np.asarray(inst["images"], np.float32)
            batch = {"images": images}

        with self._lock:
            out = self._predict(self.params, batch)
        out = jax.tree.map(lambda x: np.asarray(x)[:n], out)
        return [
            {k: v[i].tolist() for k, v in out.items()} for i in range(n)
        ]

    def metadata(self) -> dict:
        cfg = self.model.config
        return {
            "name": self.cfg.model,
            "family": self.model.family,
            "batch_size": self.cfg.batch_size,
            "config": {
                k: str(v) for k, v in vars(cfg).items()
            },
        }
