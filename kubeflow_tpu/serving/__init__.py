"""TPU model serving runtime.

Replaces the reference's serving stack — the TF ModelServer deployment
(kubeflow/tf-serving/tf-serving-template.libsonnet:29-49) plus the tornado
REST→gRPC http-proxy (components/k8s-model-server/http-proxy/server.py) —
with one process: a jitted JAX inference engine with server-side dynamic
batching (the TPU needs full batches to keep the MXU busy) behind the same
REST surface the proxy exposed (/v1/models/<m>:predict, metadata, health,
prometheus metrics).
"""

from kubeflow_tpu.serving.engine import InferenceEngine
from kubeflow_tpu.serving.server import ModelServer

__all__ = ["InferenceEngine", "ModelServer"]
