"""gRPC prediction service — the :9000 half of the dual-port serving
contract (reference: TF ModelServer exposes gRPC :9000 next to REST :8500,
kubeflow/tf-serving/tf-serving-template.libsonnet:43-49, liveness probe TCP
:9000 at :70-75).

The service is defined with grpc's generic handlers over UTF-8 JSON message
bodies rather than compiled protos — one wire format (the REST predict
schema) across both ports, no generated-stub toolchain in the serving image:

    service kubeflow.tpu.serving.PredictionService {
      rpc Predict (bytes json)          returns (bytes json);
      rpc PredictStream (bytes json)    returns (stream bytes json);
      rpc GetModelMetadata (bytes json) returns (bytes json);
    }

Predict request: ``{"model": "<name>", "instances": [...]}`` →
``{"predictions": [...]}`` — the same payloads the REST
``/v1/models/<m>:predict`` route exchanges (http-proxy PredictHandler
analogue, components/k8s-model-server/http-proxy/server.py:251-307).
"""

from __future__ import annotations

import json
from concurrent import futures

import grpc

from kubeflow_tpu.observability.tracing import (
    REQUEST_ID_HEADER,
    gen_request_id,
)

SERVICE = "kubeflow.tpu.serving.PredictionService"
DEFAULT_GRPC_PORT = 9000

_RID_KEY = REQUEST_ID_HEADER.lower()  # grpc metadata keys are lowercase


def _request_id(context) -> str:
    """X-Request-ID for a gRPC call: honor the caller's metadata value
    (the gateway/client-propagated id), mint one otherwise, and echo it
    on the initial metadata — the :9000 twin of the REST handler's
    header contract, so PR-7 tracing covers BOTH ingresses."""
    rid = ""
    for key, value in context.invocation_metadata() or ():
        if key.lower() == _RID_KEY and value:
            rid = value
            break
    rid = rid or gen_request_id()
    try:
        context.send_initial_metadata(((_RID_KEY, rid),))
    except (grpc.RpcError, ValueError):  # pragma: no cover — echo only
        pass
    return rid


def _json_bytes(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


class GrpcPredictionService:
    """Serves a :class:`~kubeflow_tpu.serving.server.ModelServer`'s engine
    over gRPC. Shares the server's batcher, so REST and gRPC requests
    coalesce into the same TPU batches."""

    # Big batches of full-vocab logits overflow grpc's 4MB default.
    MAX_MESSAGE_BYTES = 64 * 1024 * 1024

    def __init__(self, model_server, *, port: int = DEFAULT_GRPC_PORT,
                 max_workers: int = 16):
        self.model_server = model_server
        self.port = port
        self._grpc_server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", self.MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", self.MAX_MESSAGE_BYTES),
            ],
        )
        self._grpc_server.add_generic_rpc_handlers(
            (_Handler(self.model_server),)
        )
        self.bound_port = self._grpc_server.add_insecure_port(
            f"0.0.0.0:{port}"
        )
        if self.bound_port == 0 and port != 0:
            # grpc reports bind failure by returning port 0 instead of
            # raising (unlike the REST side's OSError) — surface it, or the
            # :9000 liveness probe restart-loops with no explanation.
            raise OSError(f"could not bind gRPC port {port}")

    def start(self) -> None:
        self._grpc_server.start()

    def stop(self, grace: float | None = 1.0) -> None:
        self._grpc_server.stop(grace)


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, model_server):
        self.model_server = model_server

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{SERVICE}/Predict":
            return grpc.unary_unary_rpc_method_handler(
                self._predict,
                request_deserializer=bytes,
                response_serializer=bytes,
            )
        if method == f"/{SERVICE}/PredictStream":
            return grpc.unary_stream_rpc_method_handler(
                self._predict_stream,
                request_deserializer=bytes,
                response_serializer=bytes,
            )
        if method == f"/{SERVICE}/GetModelMetadata":
            return grpc.unary_unary_rpc_method_handler(
                self._metadata,
                request_deserializer=bytes,
                response_serializer=bytes,
            )
        return None

    # ------------------------------------------------------------------

    def _parse(self, request: bytes, context) -> dict:
        try:
            body = json.loads(request or b"{}")
        except ValueError:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "request body is not valid JSON")
        if not isinstance(body, dict):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "request body must be a JSON object")
        return body

    def _predict(self, request: bytes, context) -> bytes:
        import time

        server = self.model_server
        t0 = time.perf_counter()
        error = True  # aborts raise out of the try
        try:
            body = self._parse(request, context)
            name = body.get("model") or server.engine.cfg.model
            try:
                result = server.handle_predict(
                    name, body, request_id=_request_id(context))
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except (ValueError, TimeoutError) as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            error = False
            return _json_bytes(result)
        finally:
            # Same counters as REST, so /monitoring/prometheus/metrics sees
            # :9000 traffic too.
            server.metrics.observe(time.perf_counter() - t0, error)

    def _predict_stream(self, request: bytes, context):
        """Server-streaming generation: one JSON message per token, then a
        terminal ``{"done": true}`` record — the :9000 twin of the REST
        chunked ``"stream": true`` predict."""
        import time

        server = self.model_server
        t0 = time.perf_counter()
        error = True
        try:
            body = self._parse(request, context)
            name = body.get("model") or server.engine.cfg.model
            try:
                records = server.handle_predict_stream(
                    name, body, request_id=_request_id(context))
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except (ValueError, TimeoutError) as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            for rec in records:
                yield _json_bytes(rec)
            error = False
        finally:
            server.metrics.observe(time.perf_counter() - t0, error)

    def _metadata(self, request: bytes, context) -> bytes:
        server = self.model_server
        body = self._parse(request, context)
        name = body.get("model") or server.engine.cfg.model
        try:
            return _json_bytes(server.handle_metadata(name))
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))


# ---------------------------------------------------------------------------
# Client helpers (tests, benchmarks, the gateway)
# ---------------------------------------------------------------------------


def client_stubs(channel: grpc.Channel):
    """Returns (predict, metadata) callables over an open channel."""
    predict = channel.unary_unary(
        f"/{SERVICE}/Predict",
        request_serializer=bytes,
        response_deserializer=bytes,
    )
    metadata = channel.unary_unary(
        f"/{SERVICE}/GetModelMetadata",
        request_serializer=bytes,
        response_deserializer=bytes,
    )

    def do_predict(model: str, instances: list, timeout: float = 30.0):
        resp = predict(
            _json_bytes({"model": model, "instances": instances}),
            timeout=timeout,
        )
        return json.loads(resp)

    def do_metadata(model: str, timeout: float = 10.0):
        resp = metadata(_json_bytes({"model": model}), timeout=timeout)
        return json.loads(resp)

    return do_predict, do_metadata


def stream_stub(channel: grpc.Channel):
    """Returns a callable yielding decoded records from PredictStream."""
    predict_stream = channel.unary_stream(
        f"/{SERVICE}/PredictStream",
        request_serializer=bytes,
        response_deserializer=bytes,
    )

    def do_stream(model: str, instance: dict, timeout: float = 60.0,
                  metadata=None):
        for msg in predict_stream(
            _json_bytes({"model": model, "instances": [instance]}),
            timeout=timeout,
            metadata=metadata,
        ):
            yield json.loads(msg)

    return do_stream
