"""Persistent compile cache for the serving dispatch set.

The dominant cold-start term for a scale-up replica is XLA compilation:
the continuous decoder's dispatch set (one admit executable per prefill
bucket, the fused decode/verify steps, the chunked-prefill shapes) is
recompiled from scratch by every newborn even though an identical
replica finished the exact same compiles seconds earlier. This module
keys that work by an **engine fingerprint** — a digest of everything
that selects a compiled executable: model config, mesh shape
(tp/cp/pp), KV layout/dtype, the bucket set, and the decode knobs —
and wires two layers of reuse under one directory
(``--compile-cache-dir``, a volume shared across a pool's replicas):

- **XLA's persistent compilation cache** (``jax_compilation_cache_dir``)
  holds the serialized executables themselves. Where the installed jax
  supports it, pointing it at the shared directory means the second-ever
  replica of a config deserializes instead of compiling. Wired
  best-effort: an older jax without the knob degrades to warm-by-
  dispatch, never to a crash.
- A **fingerprint-checked manifest** (this module's own store) records
  which dispatch keys a prior replica of the SAME fingerprint already
  compiled. It is the hit/miss accounting surface
  (``serving_compile_cache_{hits,misses}_total``) and the invalidation
  rule: a config change — different buckets, different mesh, different
  jax — changes the fingerprint, so stale executables are never
  *counted* as coverage and XLA's own key check never deserializes a
  mismatched binary.

The decoder pre-warms at construction by RUNNING the dispatch set
(dummy generations through the real submit path — see
``ContinuousDecoder.warm``), which populates both the in-process jit
cache and, when configured, XLA's persistent store; the manifest then
records the warmed keys for the next birth's accounting.

Manifest writes are atomic (tmp + rename) and merging, so concurrent
newborns racing on the shared volume converge instead of clobbering.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

# Manifest schema version: bump when the dispatch-key naming changes so
# old manifests read as empty instead of mis-counting coverage.
MANIFEST_VERSION = 1


def engine_fingerprint(model_config, **knobs) -> str:
    """Digest of everything that selects a compiled executable.

    ``model_config`` is the model's config dataclass (every field lands
    in the key — a d_model change is a different program); ``knobs``
    are the engine/decoder shape parameters (tp/cp/pp, kv layout/dtype,
    bucket set, decode chunk, speculative_k, ...). The jax version and
    backend ride the key too: a serialized executable is only valid for
    the compiler that produced it."""
    import jax

    payload = {
        "manifest_version": MANIFEST_VERSION,
        "model_config": {k: str(v) for k, v in
                         sorted(vars(model_config).items())},
        "knobs": {k: str(v) for k, v in sorted(knobs.items())},
        "jax": jax.__version__,
        "backend": jax.default_backend(),
    }
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def dispatch_keys(*, slots: int, prefill_len: int, prefill_len_buckets: int,
                  chunk_size: int, speculative_k: int,
                  prefill_chunk_tokens: int) -> list[str]:
    """The decoder's full dispatch set as stable string keys — one per
    distinct compiled executable shape the serving loop can reach.

    Mirrors the decoder's shape-selection rules: admit executables ride
    the pow2 prefill buckets (``prefill_len >> buckets`` floor), decode
    is one fused executable per chunk width, verify exists only under
    speculation, and chunked prefill adds its interior-chunk shape."""
    keys = []
    floor = (prefill_len >> prefill_len_buckets
             if prefill_len_buckets else prefill_len)
    width = max(1, floor)
    while True:
        keys.append(f"admit:s{width}")
        if width >= prefill_len:
            break
        width *= 2
    keys.append(f"decode:c{max(1, chunk_size)}")
    if speculative_k > 0:
        keys.append(f"verify:k{speculative_k}")
    if prefill_chunk_tokens > 0:
        keys.append(f"chunk:w{prefill_chunk_tokens}")
    return keys


def configure_jax_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``.
    Best-effort: returns False (and changes nothing) on a jax build
    without the knob — the manifest store still works, the newborn just
    pays real compiles on this host."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Serialize every executable, even fast-compiling ones: the
        # cold-start budget cares about dispatch-set *coverage*, not
        # per-executable amortization.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except (AttributeError, ValueError, TypeError):
        return False
    return True


class CompileCache:
    """Fingerprint-keyed manifest of warmed dispatch keys under a
    shared directory, plus (best-effort) the XLA persistent cache
    wiring. One instance per decoder; hit/miss counts accumulate on the
    instance and surface through the decoder's metrics."""

    def __init__(self, cache_dir: str):
        self.cache_dir = str(cache_dir)
        os.makedirs(self.cache_dir, exist_ok=True)
        # XLA's serialized executables live next to the manifests; a
        # failure to wire it leaves warm-by-dispatch as the whole story.
        self.xla_cache_wired = configure_jax_cache(
            os.path.join(self.cache_dir, "xla"))
        self.hits = 0
        self.misses = 0

    def _manifest_path(self, fingerprint: str) -> str:
        return os.path.join(self.cache_dir, f"manifest-{fingerprint}.json")

    def load(self, fingerprint: str) -> set[str]:
        """Dispatch keys a prior replica of this fingerprint recorded.
        A torn/garbage manifest reads as empty — the newborn then just
        compiles; it must never crash a birth."""
        try:
            with open(self._manifest_path(fingerprint)) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return set()
        if not isinstance(data, dict) or \
                data.get("version") != MANIFEST_VERSION:
            return set()
        keys = data.get("keys")
        return {str(k) for k in keys} if isinstance(keys, list) else set()

    def record(self, fingerprint: str, keys) -> None:
        """Merge ``keys`` into the fingerprint's manifest atomically
        (tmp + rename): concurrent newborns on the shared volume merge
        with whatever landed since their read instead of clobbering."""
        merged = self.load(fingerprint) | {str(k) for k in keys}
        payload = {"version": MANIFEST_VERSION,
                   "fingerprint": fingerprint,
                   "keys": sorted(merged)}
        fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self._manifest_path(fingerprint))
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def account(self, fingerprint: str, keys) -> tuple[int, int]:
        """Split ``keys`` against the manifest: (hits, misses). Hits are
        keys a prior same-fingerprint replica already compiled (this
        birth deserializes / reuses); misses are newly compiled here and
        recorded for the next birth."""
        known = self.load(fingerprint)
        keys = [str(k) for k in keys]
        hits = sum(1 for k in keys if k in known)
        misses = len(keys) - hits
        self.hits += hits
        self.misses += misses
        if misses:
            self.record(fingerprint, keys)
        return hits, misses
