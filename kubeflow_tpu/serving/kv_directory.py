"""Fleet-wide prefix→holder directory for the distributed KV economy.

The replicas of a serving fleet each carry a private prefix trie and
host-RAM tier (serving/prefix_cache.py, serving/kv_tier.py); this module
is the piece that makes them a FLEET cache: a bounded map from
``prefix_affinity_key`` values (serving/affinity.py — the same keys the
gateway's prefix-affine router already computes per request) to the
replicas believed to hold KV for that key range, plus the cold
content-addressed tier (serving/cold_store.py).

The directory stores HINTS, not truth. A holder entry records the
deepest prefix length a replica advertised for a key, the weights epoch
the bytes were computed under, and which tier held them at publish time
— but the authoritative check is the pull itself: a requester that
imports from a holder validates tokens, block metadata, and epoch on
the fetched envelope, and a miss (holder evicted meanwhile, holder
dead, epoch moved on) simply withdraws the hint and falls through to
the next tier. Wrong hints cost one wasted probe; they can never
corrupt KV. That tolerance is what lets publishes stay cheap
(lock-then-dict-write, no fleet round-trip) on the decoder's hot
eviction/publish paths.

Shared across threads — the gateway's proxy handlers, every replica's
caller-thread submit probes, and the fleet's death sweeps all touch it
— so unlike the trie/tier (caller-serialized), the directory carries
its own leaf lock: no method calls out while holding it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass

# Sentinel holder name for bytes that demoted into the shared cold
# store: no replica serves them, requesters probe the cold tier
# directly, but the published hint keeps the key's history visible to
# rollup dashboards (and lets the gateway know the prefix exists at
# all, even with every warm holder gone).
COLD_HOLDER = "<cold>"


@dataclass
class DirectoryHint:
    """One holder's claim on a key: the deepest prefix it advertised,
    the weights epoch that computed the bytes, and the tier they lived
    in at publish time (``hbm``/``host``/``cold``/``route``)."""

    holder: str
    prefix_len: int
    version: int
    tier: str


class KvDirectory:
    """Bounded LRU map: affinity key → {holder → :class:`DirectoryHint`}.

    ``capacity`` bounds the number of distinct KEYS tracked (each key
    holds at most one hint per holder); publishing past it evicts the
    least-recently-touched key — a directory is a cache of routing
    hints, and a forgotten key merely degrades to the pre-directory
    behavior (local tiers, then prefill).
    """

    def __init__(self, capacity: int = 1024):
        if capacity <= 0:
            raise ValueError("KvDirectory needs a positive capacity")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._keys: OrderedDict[str, dict[str, DirectoryHint]] = \
            OrderedDict()
        self.publishes = 0
        self.withdrawals = 0
        self.hits = 0        # lookups that returned at least one hint
        self.misses = 0      # lookups that found nothing usable
        self.evictions = 0   # keys dropped by the capacity bound
        self.holder_drops = 0  # drop_holder sweeps (replica deaths)

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    # -- publish / withdraw --------------------------------------------

    def publish(self, key: str, holder: str, *, prefix_len: int = 0,
                version: int = 0, tier: str = "hbm") -> None:
        """Record (or deepen/refresh) ``holder``'s claim on ``key``.
        A re-publish keeps the deepest prefix length seen for the same
        epoch — a holder's shallower advert never shrinks its claim —
        but an epoch change REPLACES the hint outright: old-epoch bytes
        are unservable, so their depth is no longer evidence."""
        holder = str(holder)
        if not holder:
            return
        with self._lock:
            hints = self._keys.get(key)
            if hints is None:
                hints = self._keys[key] = {}
            old = hints.get(holder)
            if (old is not None and old.version == int(version)
                    and old.prefix_len > int(prefix_len)):
                prefix_len = old.prefix_len
            hints[holder] = DirectoryHint(
                holder=holder, prefix_len=int(prefix_len),
                version=int(version), tier=str(tier))
            self._keys.move_to_end(key)
            self.publishes += 1
            while len(self._keys) > self.capacity:
                self._keys.popitem(last=False)
                self.evictions += 1

    def withdraw(self, key: str, holder: str) -> None:
        """Remove one holder's claim on ``key`` (a pull against the
        hint came back empty — the holder evicted or moved epochs)."""
        with self._lock:
            hints = self._keys.get(key)
            if hints is None:
                return
            if hints.pop(str(holder), None) is not None:
                self.withdrawals += 1
            if not hints:
                del self._keys[key]

    def drop_holder(self, holder: str) -> None:
        """Sweep every hint naming ``holder`` — a replica died; its
        advertised bytes are gone with it. Cold hints survive (the
        cold store outlives any one replica)."""
        holder = str(holder)
        with self._lock:
            empty = []
            for key, hints in self._keys.items():
                hints.pop(holder, None)
                if not hints:
                    empty.append(key)
            for key in empty:
                del self._keys[key]
            self.holder_drops += 1

    # -- lookup --------------------------------------------------------

    def lookup(self, key: str, *, exclude: tuple = (),
               version: int | None = None) -> list[DirectoryHint]:
        """Hints for ``key``, deepest first. ``exclude`` filters holder
        names (a replica never pulls from itself); ``version`` (when
        given) filters hints stamped with a different weights epoch —
        pre-swap bytes would be refused at import anyway, so probing
        their holders is pure waste."""
        excluded = set(exclude)
        with self._lock:
            hints = self._keys.get(key)
            if not hints:
                self.misses += 1
                return []
            self._keys.move_to_end(key)
            out = [h for h in hints.values()
                   if h.holder not in excluded
                   and (version is None or h.version == int(version))]
            if out:
                self.hits += 1
            else:
                self.misses += 1
        return sorted(out, key=lambda h: (-h.prefix_len, h.holder))

    def holders(self, key: str, *, version: int | None = None,
                warm_only: bool = True) -> list[str]:
        """Holder names for ``key`` (the gateway's spill preference —
        it needs names, not depths). ``warm_only`` skips the cold
        sentinel: you cannot route a request to an object store."""
        return [h.holder
                for h in self.lookup(key, version=version)
                if not (warm_only and h.holder == COLD_HOLDER)]

    def stats(self) -> dict:
        with self._lock:
            return {
                "keys": len(self._keys),
                "capacity": self.capacity,
                "publishes": self.publishes,
                "withdrawals": self.withdrawals,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "holder_drops": self.holder_drops,
            }
