"""Continuous-batching decode service with per-token streaming.

TPU-first continuous batching (the capability vLLM/JetStream serve on GPUs,
built the XLA way): a persistent fixed-shape decode state holds up to
``slots`` in-flight sequences, and every decode step is ONE compiled
``[slots, 1]`` forward against the shared KV cache
(:func:`kubeflow_tpu.models.decode.decode_step`). Pending requests are
prefilled at fixed prompt shape — a round's admissions TOGETHER in one
power-of-two-bucketed batch, fused with the state insert into a single
dispatch (``admit_rows``: one round-trip per round, not two per
request) — landing in free rows at step boundaries; a finished row
frees its slot immediately, so a 1-token
request never waits on a 32-token peer — the decoupling VERDICT round 2
asked for over the lockstep batch path (serving/engine.py:_generate_batch).

Two admission-cost levers ride on top (both off by default): a
device-resident **prefix KV cache** (``prefix_cache_slots``) that lets a
prompt whose leading tokens are already pooled gather those K/V rows and
prefill only its suffix (host trie in serving/prefix_cache.py, device
pool + gather/scatter in models/decode.py, publish-on-finish, LRU with
in-flight pins), and **power-of-two prefill length buckets**
(``prefill_len_buckets``) so a short prompt rides a short compiled shape
instead of padding to the full ``prefill_len``.

Decode itself has a throughput lever (off by default): **speculative
decoding** (``speculative_k``). A pluggable proposer
(serving/speculative.py: host n-gram lookup or a small draft model)
guesses up to K tokens per row each round, and ONE fused verify dispatch
(models/decode.py:verify_step) scores them all, keeping each row's
longest accepted prefix plus one committed target token — up to K+1
tokens per dispatch against decode's memory-bandwidth bill of one.
Greedy outputs are byte-identical to speculation off; temperature>0 rows
rejection-resample so their distribution is unchanged. Per-slot draft
length auto-tunes (shrinks while a row's drafts keep missing, recovers
on clean sweeps), and accept/draft counters land in :meth:`metrics`.

Two multi-tenant levers ride the paged pool (both off by default):
a **host-RAM KV tier** (``host_kv_bytes``, serving/kv_tier.py) that
demotes evicted prefix blocks to host memory instead of freeing them
outright — a later trie miss re-imports them through the ordinary
prefix-hit admission, so the effective pool rises past HBM at equal
device bytes — and **QoS admission** (``qos``, serving/qos.py):
per-tenant token buckets at submit, weighted-fair + priority + aging
ordering of the pending queue, deadline shedding, and — under
low-watermark pressure — SUSPENSION of the lowest-priority live stream
(export its KV to the host tier, free its slot and blocks, park the
request) instead of deferring the whole queue; the parked stream
resumes byte-identically through the same prefix-hit admission.

Tokens surface through per-request queues as each step's sample lands —
the REST server streams them as JSON lines over chunked transfer-encoding
and gRPC as a server-streaming method. The reference serves generation
through TF-Serving's opaque batcher (kubeflow/tf-serving/
tf-serving-template.libsonnet:29-49); this is the platform-native engine
with the serving loop exposed.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.decode import (
    admit_prefix_and_step,
    admit_rows_and_step,
    copy_block,
    decode_chunk,
    decode_step,
    export_blocks,
    import_blocks,
    init_decode_state,
    init_paged_state,
    init_prefix_pool,
    paged_admit_prefix_and_step,
    paged_admit_rows_and_step,
    paged_prefill_chunk,
    prefill,
    retire_row,
    shard_decode_state,
    store_blocks,
    store_prefix_cache,
    store_prefix_row,
    verify_chunk,
)
from kubeflow_tpu.observability.metrics import MetricRegistry
from kubeflow_tpu.observability.tracing import TraceStore
from kubeflow_tpu.serving.affinity import (
    DEFAULT_AFFINITY_TOKENS,
    prefix_affinity_key,
)
from kubeflow_tpu.serving.engine import pow2_bucket
from kubeflow_tpu.serving.kv_allocator import (
    BlockAllocator,
    kv_bytes_per_token,
)
from kubeflow_tpu.serving.kv_directory import COLD_HOLDER
from kubeflow_tpu.serving.kv_tier import HostKvTier, payload_nbytes
from kubeflow_tpu.serving.prefix_cache import PrefixCache
from kubeflow_tpu.serving.qos import (
    DEFAULT_TENANT,
    DeadlineExceeded,
    QosPolicy,
    order_key,
    tenant_bucket,
)
from kubeflow_tpu.serving.speculative import make_proposer

_DONE = object()


class PromptTooLong(ValueError):
    """Terminal admission error: the prompt cannot be served by this
    replica at all — it needs more KV blocks than the whole pool holds,
    or its tokens plus the requested budget exceed the virtual row
    width — so deferring would wait forever. The model server maps this
    to HTTP 413 (vs. the silent-defer path memory PRESSURE takes)."""


@dataclass
class _Request:
    tokens: list[int]
    want: int
    temperature: float
    stream: queue.Queue = field(default_factory=queue.Queue)
    out: list[int] = field(default_factory=list)
    prefill_logits: np.ndarray | None = None
    # Lazy source for prefill_logits: (device array [K, V], row). The
    # vocab-wide logits are ~128KB/row — fetching them eagerly for every
    # admission cost more tunnel time than the whole decode; only the
    # callers that actually read them (want==0 scoring, return_logits)
    # should pay.
    prefill_src: tuple | None = None
    error: Exception | None = None
    # Prefix-cache entry this request's admission read (pinned against
    # eviction until the request finishes).
    pinned_prefix: object | None = None
    # Paged layout: (entry, prefix_len, suffix_bucket) planned at pop
    # time — the plan must precede the block reservation so the entry
    # is pinned before memory-pressure reclaim runs, and so the
    # reservation only covers the NON-shared block count.
    admit_plan: tuple | None = None
    done: threading.Event = field(default_factory=threading.Event)
    submit_t: float = field(default_factory=time.perf_counter)
    ttft_s: float | None = None
    finish_reason: str = "length"
    # Request-scoped trace: id propagated from the gateway (or minted at
    # submit) + the lifecycle timeline recorded into the decoder's
    # TraceStore. last_emit_t feeds the inter-token histogram.
    request_id: str = ""
    timeline: object | None = None
    last_emit_t: float | None = None
    # QoS: owning tenant, base priority (tenant default unless the
    # request carried its own), and an absolute shed deadline (None =
    # never shed). ``defer_rounds`` counts rounds this request sat at
    # the head of admission blocked on memory — the HoL-bypass aging
    # counter. ``host_key`` is set while the stream is SUSPENDED: the
    # pinned host-tier entry its resume re-imports.
    tenant: str = DEFAULT_TENANT
    priority: int = 0
    deadline_t: float | None = None
    defer_rounds: int = 0
    host_key: tuple | None = None
    # Emitted tokens already folded into ``tokens`` by an earlier
    # suspension — a later suspension must append only out[folded:],
    # never double-count the first park's fold.
    folded: int = 0
    # Chunked prefill: prompt tokens already scattered into this
    # request's blocks (-1 = not a chunked admission / chain finished).
    # While >= 0 the slot's device row is PARKED (length=total,
    # active=False) and the request must not be suspend-victimized.
    chunk_pos: int = -1
    # True once the first chunk's dispatch stamped the weights epoch,
    # CoW'd the shared tail, and uploaded the table row.
    chunk_started: bool = False
    # Weights epoch this request's PREFILL ran under (stamped inside
    # the admission dispatch's state-lock scope). A finishing stream
    # only publishes its prompt K/V into the prefix trie when this
    # still matches the decoder's live version — a stream that
    # straddled a weight swap computed its prompt K/V under weights
    # the decoder no longer serves.
    weights_version: int = 0

    @property
    def want_left(self) -> int:
        """Tokens still owed. Equals ``want`` for a fresh request; a
        resumed (previously suspended) request already emitted
        ``len(out)`` of its budget, and the device row must only be
        armed for the remainder."""
        return max(self.want - len(self.out), 0)

    def resolve_prefill_logits(self) -> np.ndarray | None:
        if self.prefill_logits is None and self.prefill_src is not None:
            arr, row = self.prefill_src
            self.prefill_logits = np.asarray(arr[row])
            self.prefill_src = None
        return self.prefill_logits


class StreamHandle:
    """Caller-side view of an in-flight generation.

    ``default_timeout`` is the decoder's ``stream_timeout_s`` — callers
    that pass no explicit timeout inherit it, so a deployment expecting
    memory-deferred admissions under load can raise ONE knob instead of
    chasing hard-coded 60s waits through every caller.
    """

    def __init__(self, req: _Request, default_timeout: float = 60.0):
        self._req = req
        self._default_timeout = default_timeout

    def tokens(self, timeout: float | None = None):
        """Yield tokens as the decode loop emits them."""
        if timeout is None:
            timeout = self._default_timeout
        while True:
            try:
                item = self._req.stream.get(timeout=timeout)
            except queue.Empty:
                # queue.Empty's str() is blank — surface a real timeout.
                raise TimeoutError("token stream timed out") from None
            if item is _DONE:
                if self._req.error is not None:
                    raise self._req.error
                return
            yield item

    def result(self, timeout: float | None = None, *,
               with_logits: bool | None = None) -> dict:
        """Block until the request finishes; returns the full prediction.

        ``with_logits``: fetch the vocab-wide prefill logits (a ~128KB
        device transfer). Default None = only when the request emitted
        no tokens (pure-prefill scoring, where the logits ARE the
        answer); pass True to force (return_logits callers).
        """
        if timeout is None:
            timeout = self._default_timeout
        if not self._req.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if self._req.error is not None:
            raise self._req.error
        need = with_logits or (with_logits is None and not self._req.out)
        return {
            "tokens": list(self._req.out),
            "prefill_logits": (self._req.resolve_prefill_logits()
                               if need else self._req.prefill_logits),
            "ttft_s": self._req.ttft_s,
            "finish_reason": self._req.finish_reason,
        }

    @property
    def ttft_s(self) -> float | None:
        return self._req.ttft_s


class ContinuousDecoder:
    """Owns the device decode state and the scheduler thread.

    ``prefill_len`` fixes the compiled prompt shape (prompts are right-padded
    to it); ``slots`` is the decode concurrency; total cache length is
    ``prefill_len + max_new_tokens``.
    """

    def __init__(self, params, cfg, *, slots: int, prefill_len: int,
                 max_new_tokens: int, top_k: int = 0,
                 eos_id: int | None = None, seed: int = 0,
                 chunk_size: int = 1, prefix_cache_slots: int = 0,
                 prefix_cache_min_len: int = 16,
                 prefill_len_buckets: int = 0, speculative_k: int = 0,
                 draft_mode: str = "ngram", kv_layout: str = "dense",
                 kv_block_size: int = 16, kv_pool_blocks: int = 0,
                 kv_low_watermark: int = 0, kv_dtype: str = "fp",
                 kv_fused: bool = False,
                 stream_timeout_s: float = 60.0,
                 role: str = "", tp_shards: int = 1,
                 qos: QosPolicy | None = None,
                 host_kv_bytes: int = 0,
                 hol_bypass_limit: int = 4,
                 hol_shield_rounds: int = 8,
                 prefill_chunk_tokens: int = 0,
                 max_prompt_len: int = 0,
                 cp_shards: int = 1,
                 pp_stages: int = 1,
                 kv_directory=None,
                 cold_store=None,
                 peer_fetch=None,
                 kv_import_crossover_tokens: int = 0,
                 kv_affinity_tokens: int = 0,
                 replica_name: str = "",
                 boot_weights_version: int = 0,
                 compile_cache_dir: str = ""):
        # Model-parallel serving: tp_shards > 1 runs THIS replica's
        # decode executables over a tp-wide tensor mesh — weights carry
        # the Megatron column/row split from the model's partition
        # rules, and the KV storage is sharded over the KV-HEAD axis.
        # Block ids index the unsharded block dim, so the allocator,
        # prefix trie, refcount/CoW, and export/import handoff all run
        # unchanged on host-global ids; only bytes-per-token (per-chip
        # HBM) and the fused kernel's read path know about the split.
        # cp_shards > 1 adds a `sequence` axis outside the tensor axis:
        # chunked-prefill attention runs ring-style over it (weights and
        # KV replicated across cp — cp buys PREFILL FLOPs/bandwidth for
        # long prompts, not HBM capacity). pp_stages > 1 adds the
        # outermost `pipeline` axis: the stacked layer weights AND the
        # KV pool's leading layer dim shard over it, so per-chip weight
        # and KV bytes divide by pp while the host-side allocator still
        # sees whole (all-layer) logical blocks.
        self.tp_shards = max(1, int(tp_shards))
        self.cp_shards = max(1, int(cp_shards))
        self.pp_stages = max(1, int(pp_stages))
        if self.tp_shards > 1:
            if cfg.n_kv_heads % self.tp_shards:
                raise ValueError(
                    f"tp_shards {self.tp_shards} must divide n_kv_heads "
                    f"{cfg.n_kv_heads} (the KV pool shards by head)")
            if cfg.n_heads % self.tp_shards:
                raise ValueError(
                    f"tp_shards {self.tp_shards} must divide n_heads "
                    f"{cfg.n_heads}")
            if cfg.d_ff % self.tp_shards:
                raise ValueError(
                    f"tp_shards {self.tp_shards} must divide d_ff "
                    f"{cfg.d_ff}")
        if self.cp_shards > 1:
            if self.cp_shards & (self.cp_shards - 1):
                raise ValueError(
                    f"cp_shards {self.cp_shards} must be a power of two "
                    "(ring shards ride the pow2 chunk buckets)")
            if kv_layout != "paged":
                raise ValueError("cp_shards > 1 requires kv_layout="
                                 "'paged' (the ring reads the gathered "
                                 "paged span)")
            if kv_fused:
                raise ValueError(
                    "cp_shards > 1 uses the gathered ring read; it does "
                    "not compose with kv_fused")
            if not prefill_chunk_tokens:
                raise ValueError(
                    "cp_shards > 1 shards chunked-prefill attention; "
                    "set prefill_chunk_tokens > 0")
        if self.pp_stages > 1:
            if kv_fused:
                raise ValueError(
                    "pp_stages > 1 does not compose with kv_fused (the "
                    "fused kernel assumes an unsharded layer dim)")
            from kubeflow_tpu.parallel.pipeline import stage_layer_ranges

            # Raises unless n_layers divides evenly; the ranges are the
            # per-stage KV accounting documented in docs/serving.md.
            stage_layer_ranges(cfg.n_layers, self.pp_stages)
            cfg = dataclasses.replace(cfg,
                                      pipeline_stages=self.pp_stages)
        if self.tp_shards > 1 or self.cp_shards > 1 or self.pp_stages > 1:
            from kubeflow_tpu.models.transformer import partition_rules
            from kubeflow_tpu.parallel.mesh import serving_mesh
            from kubeflow_tpu.parallel.sharding import shard_pytree

            self.mesh = serving_mesh(self.tp_shards, cp=self.cp_shards,
                                     pp=self.pp_stages)
            params = shard_pytree(params, self.mesh, partition_rules(cfg))
        else:
            self.mesh = None
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.prefill_len = prefill_len
        self.max_new_tokens = max_new_tokens
        self.top_k = top_k
        self.eos_id = eos_id
        self.stream_timeout_s = float(stream_timeout_s)
        # Power-of-two prefill length buckets (0 = every prompt pads to
        # prefill_len): a round's prompts ride the smallest allowed
        # compiled shape covering them, so a 6-token prompt stops paying
        # a 128-token prefill. Bucket floor = prefill_len >> buckets.
        self.prefill_len_buckets = max(0, int(prefill_len_buckets))
        # Device-resident prefix KV cache: host trie -> pool row of
        # cached prefix K/V. Admissions that match reuse the rows and
        # prefill only their suffix; finished prompts publish back.
        if kv_layout not in ("dense", "paged"):
            raise ValueError(f"unknown kv_layout {kv_layout!r}")
        self.kv_layout = kv_layout
        # KV residency precision: "fp" keeps the model dtype (bitwise
        # parity with dense pinned in tests); "int8" stores blocks
        # quantized with per-position per-head scales, roughly doubling
        # blocks per HBM byte at a pinned greedy-token tolerance.
        if kv_dtype not in ("fp", "int8"):
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
        if kv_dtype == "int8" and kv_layout != "paged":
            raise ValueError("kv_dtype='int8' requires kv_layout='paged'")
        self.kv_dtype = kv_dtype
        # Fused block-table attention for the paged decode step: the
        # kernel walks the table (int8 dequantized in-register) instead
        # of gathering the dense [slots, total_len] view each step. Off
        # by default — the gather path is the pinned-accuracy reference
        # (bitwise for fp blocks).
        if kv_fused and kv_layout != "paged":
            raise ValueError("kv_fused requires kv_layout='paged'")
        self.kv_fused = bool(kv_fused)
        # Disaggregated-fleet role: "" (colocated, the default),
        # "prefill" (prompt admission only — peers pull finished prompt
        # KV via export_prompt) or "decode" (resumes imported prompts).
        # The handoff rides the paged block pool, so a role requires it.
        if role not in ("", "prefill", "decode"):
            raise ValueError(f"unknown role {role!r}")
        if role and kv_layout != "paged":
            raise ValueError("a fleet role requires kv_layout='paged'")
        self.role = role
        self.prefix_cache = (
            PrefixCache(prefix_cache_slots, min_len=prefix_cache_min_len)
            if prefix_cache_slots > 0 else None
        )
        # Dense layout only: the prefix pool is a second full-width copy
        # of each cached prefix. The paged layout supersedes it — a hit
        # SHARES the donor's pool blocks by refcount (zero device
        # copies), so the main pool is the only KV storage.
        self._prefix_pool = (
            init_prefix_pool(cfg, prefix_cache_slots, prefill_len)
            if prefix_cache_slots > 0 and kv_layout == "dense" else None
        )
        # Guards trie + pool-reference mutation: prime_prefix() runs on
        # caller threads while the scheduler thread matches/publishes.
        self._prefix_lock = threading.Lock()
        # Decode steps fused per device dispatch. 1 = one dispatch per
        # token (finest admission/streaming granularity — right for a
        # local TPU where a dispatch is sub-ms). K>1 trades admission
        # latency (a new request waits up to K steps) for K× fewer
        # round-trips — the remote-dispatch/high-RTT configuration
        # (VERDICT r3 #5; measured in bench_serving.py --generate).
        # EOS parking moves on-device inside the fused loop either way.
        self.chunk_size = max(1, int(chunk_size))
        # Long-context serving: prefill_chunk_tokens > 0 admits any
        # prompt whose (post-prefix) suffix exceeds it as a CHAIN of
        # bounded chunk dispatches interleaved with decode rounds — the
        # chunk width is the worst-case gap a long admission can insert
        # into a live stream's inter-token cadence. max_prompt_len
        # raises the prompt ceiling past the compiled prefill width
        # (chunks ride the paged block scatter, so only the virtual row
        # width — not any compiled shape — bounds the prompt).
        self.prefill_chunk_tokens = max(0, int(prefill_chunk_tokens))
        if self.prefill_chunk_tokens:
            if kv_layout != "paged":
                raise ValueError(
                    "prefill_chunk_tokens requires kv_layout='paged' "
                    "(chunks scatter into the block pool)")
            if self.prefill_chunk_tokens > prefill_len:
                raise ValueError(
                    f"prefill_chunk_tokens {self.prefill_chunk_tokens} "
                    f"must be <= prefill_len {prefill_len} (chunks ride "
                    "the compiled suffix buckets)")
        self.max_prompt_len = int(max_prompt_len) or prefill_len
        if self.max_prompt_len < prefill_len:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} must be >= "
                f"prefill_len {prefill_len}")
        if self.max_prompt_len > prefill_len and not self.prefill_chunk_tokens:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} > prefill_len "
                f"{prefill_len} requires prefill_chunk_tokens > 0 "
                "(monolithic prefill is bounded by the compiled width)")
        self.total_len = self.max_prompt_len + max_new_tokens
        if self.cp_shards > 1:
            floor = (prefill_len >> self.prefill_len_buckets
                     if self.prefill_len_buckets
                     else min(8, prefill_len))
            if floor % self.cp_shards:
                raise ValueError(
                    f"cp_shards {self.cp_shards} must divide the suffix "
                    f"bucket floor {floor} (every chunk dispatch shards "
                    "its query tokens over the sequence axis)")
            if self.total_len % self.cp_shards:
                raise ValueError(
                    f"cp_shards {self.cp_shards} must divide "
                    f"max_prompt_len + max_new_tokens = {self.total_len} "
                    "(the ring streams the gathered virtual row)")
        # Speculative decoding: K>0 turns decode rounds into verify
        # rounds whenever the proposer has drafts — one fused dispatch
        # scores up to K draft tokens per row (chunk_size>1 fuses that
        # many verify steps per dispatch, mirroring decode_chunk).
        self.speculative_k = max(0, int(speculative_k))
        self._verify_steps = self.chunk_size if self.chunk_size > 1 else 1
        self._spec = (
            make_proposer(
                draft_mode, target_vocab=cfg.vocab_size, slots=slots,
                total_len=self.total_len,
                propose_steps=(self._verify_steps * self.speculative_k
                               + self._verify_steps - 1),
                seed=seed)
            if self.speculative_k > 0 else None
        )
        # Per-slot draft length, auto-tuned in [1, speculative_k]: shrink
        # while a row's drafts keep missing (verify compute is then pure
        # overhead), recover on clean sweeps.
        self._slot_k = [self.speculative_k] * slots
        if kv_layout == "paged":
            self.kv_block_size = max(1, int(kv_block_size))
            if self.total_len % self.kv_block_size:
                raise ValueError(
                    f"kv_block_size {self.kv_block_size} must divide "
                    f"max_prompt_len + max_new_tokens = {self.total_len} "
                    "(equal virtual row width is what makes paged decode "
                    "byte-identical to dense)")
            mb = self.total_len // self.kv_block_size
            # 0 = worst-case parity with the dense reservation: the pool
            # can back every slot at full length, so paged is never more
            # restrictive than dense. Smaller pools trade that for HBM;
            # larger slots counts then buy real concurrency.
            num_blocks = int(kv_pool_blocks) or slots * mb
            if num_blocks < mb:
                raise ValueError(
                    f"kv_pool_blocks {num_blocks} cannot back even one "
                    f"worst-case sequence ({mb} blocks)")
            # Bytes are priced PER CHIP: a tp-sharded pool holds
            # Hkv / tp heads per position on each chip, and the fill
            # gauges must reflect the HBM a chip actually spends.
            self._alloc = BlockAllocator(
                num_blocks, self.kv_block_size,
                bytes_per_token=kv_bytes_per_token(
                    cfg.n_layers, cfg.n_kv_heads, cfg.head_dim,
                    jnp.dtype(cfg.dtype).itemsize, kv_dtype,
                    tp_shards=self.tp_shards))
            self._max_blocks_per_seq = mb
            # Host mirror of the device block table; sentinel
            # ``num_blocks`` marks unallocated entries (writes through
            # them are dropped on device).
            self._table = np.full((slots, mb), num_blocks, np.int32)
            self._slot_blocks: list[list[int]] = [[] for _ in range(slots)]
            self._state = init_paged_state(cfg, slots, num_blocks,
                                           self.kv_block_size, mb, seed,
                                           kv_dtype=kv_dtype)
        else:
            self.kv_block_size = int(kv_block_size)
            self._alloc = None
            self._state = init_decode_state(cfg, slots, self.total_len, seed)
        if self.mesh is not None:
            # KV payload onto the mesh, head-sharded (and layer-sharded
            # over `pipeline` when pp > 1); scalars/tables/RNG
            # replicated. Every jitted step's computation then follows
            # its committed inputs onto the mesh. The `sequence` axis is
            # named nowhere in the state specs — KV replicates across
            # cp, and only the chunked-prefill ring read partitions it.
            pp_axis = "pipeline" if self.pp_stages > 1 else None
            self._state = shard_decode_state(self._state, self.mesh,
                                             pp_axis=pp_axis)
            if self._prefix_pool is not None:
                self._prefix_pool = shard_decode_state(self._prefix_pool,
                                                       self.mesh,
                                                       pp_axis=pp_axis)
        # The fused block-table kernel walks its mesh twin only under a
        # tensor mesh; the gather path partitions under plain GSPMD.
        self._kmesh = self.mesh if self.kv_fused else None
        # Ring mesh for chunk dispatches: only cp > 1 routes the chunk's
        # span attention through the sequence-axis ring (decode steps
        # stay on the plain GSPMD path regardless).
        self._ring = self.mesh if self.cp_shards > 1 else None
        self.kv_low_watermark = max(0, int(kv_low_watermark))
        # Multi-tenant QoS: token-bucket admission at submit, weighted-
        # fair/priority/aging ordering of the pending queue, deadline
        # shedding, and suspension of low-priority live streams under
        # memory pressure (requires the host tier below to park KV).
        self.qos = qos
        # Host-RAM KV tier (HBM -> host): trie evictions demote their
        # blocks here instead of freeing outright, trie misses probe it
        # before cold prefill, and suspended streams pin their exported
        # KV here until resume. 0 disables.
        if host_kv_bytes and kv_layout != "paged":
            raise ValueError("host_kv_bytes requires kv_layout='paged'")
        self._host_tier = (HostKvTier(int(host_kv_bytes))
                           if host_kv_bytes else None)
        # Host-global bytes one tiered token costs (the tier holds the
        # gathered, unsharded payload even under tp).
        self._host_bytes_per_token = (
            kv_bytes_per_token(cfg.n_layers, cfg.n_kv_heads, cfg.head_dim,
                               jnp.dtype(cfg.dtype).itemsize, kv_dtype)
            if self._alloc is not None else 0)
        # Fleet KV economy (HBM -> host -> PEER -> COLD): the shared
        # prefix->holder directory (serving/kv_directory.py), the
        # content-addressed cold store (serving/cold_store.py), and the
        # peer-pull callable the fleet/server wires in
        # (``peer_fetch(holder, tokens, version) -> {"envelope": packed,
        # "weights_version": v} | None``). A local trie+host miss probes
        # the directory ON THE CALLER THREAD in submit() — never under
        # a decoder lock — and installs the imported prefix so the
        # pop-time plan sees an ordinary trie hit.
        # ``kv_import_crossover_tokens`` is the recompute-vs-import
        # crossover: the per-pull fixed cost (RTT + envelope
        # pack/unpack + scatter dispatch) amortizes over matched
        # tokens, so importing pays only when the remote match beats
        # the best LOCAL tier by at least this many tokens (0 = any
        # strictly-deeper match imports).
        if (kv_directory is not None or cold_store is not None) \
                and kv_layout != "paged":
            raise ValueError(
                "the fleet KV economy (kv_directory/cold_store) "
                "requires kv_layout='paged'")
        self.kv_directory = kv_directory
        self.cold_store = cold_store
        self._peer_fetch = peer_fetch
        self.replica_name = str(replica_name or "")
        self.kv_import_crossover_tokens = max(
            0, int(kv_import_crossover_tokens))
        self.kv_affinity_tokens = (int(kv_affinity_tokens)
                                   or DEFAULT_AFFINITY_TOKENS)
        # Head-of-line bypass: how many memory-blocked candidates a
        # round may skip past looking for a smaller request that fits,
        # and how many blocked rounds age a head into an unskippable
        # shield (so bypass can never starve the big request).
        self.hol_bypass_limit = max(0, int(hol_bypass_limit))
        self.hol_shield_rounds = max(1, int(hol_shield_rounds))
        # Serializes device access to self._state between the scheduler
        # thread and caller-thread prime_prefix (which, in paged mode,
        # writes primed blocks into the SHARED pool — the jitted calls
        # donate state buffers, so unsynchronized access would read
        # donated storage).
        self._state_lock = threading.Lock()
        self._slot_req: list[_Request | None] = [None] * slots
        self._active_count = 0
        self._pending: deque[_Request] = deque()
        # In-flight chunked admissions: (req, slot) in arrival order.
        # Scheduler-thread-only writes; the pop loop advances the OLDEST
        # job by exactly one chunk per round, so a long admission never
        # inserts more than one chunk between decode dispatches.
        self._chunk_jobs: list[tuple[_Request, int]] = []
        self._cv = threading.Condition()
        self._stopped = False
        # Serving metrics (scraped via the model server's /monitoring route).
        self.tokens_emitted = 0
        self.steps = 0       # device decode steps (incl. masked chunk tail)
        self.dispatches = 0  # device round-trips (the tunnel-cost metric)
        self.prefill_dispatches = 0  # admission round-trips (fused)
        self.admitted = 0            # requests admitted
        self.prefill_tokens = 0      # real prompt tokens actually prefilled
        self.prefill_chunks = 0      # interior chunk dispatches (long prompts)
        self.prompt_rejected_too_long = 0  # PromptTooLong terminal rejections
        # Prefix-cache counters (all zero when the cache is disabled).
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_reused = 0   # prompt tokens served from the pool
        self.prefix_suffix_tokens = 0   # suffix tokens prefilled on hits
        self.prefix_inserts = 0         # prefixes published to the pool
        self.ramp_rounds = 0         # admission-only (no-chunk) rounds
        # Speculative-decoding counters (zero when speculation is off).
        self.spec_drafted_tokens = 0    # draft tokens submitted to verify
        self.spec_accepted_tokens = 0   # draft tokens the target kept
        self.spec_verify_dispatches = 0  # fused verify round-trips
        self.ttft_sum = 0.0
        self.ttft_count = 0
        # Paged-KV counters (zero in the dense layout).
        self.kv_cow_copies = 0       # tail-block copy-on-writes
        self.kv_shared_blocks = 0    # blocks mapped by refcount on hits
        self.kv_defer_admissions = 0  # rounds deferred for memory
        # Disaggregated handoff counters (zero outside a role split).
        self.kv_handoff_exports = 0   # prompts exported to a decode peer
        self.kv_handoff_imports = 0   # prompts imported from a prefill peer
        self.kv_handoff_tokens = 0    # prefix tokens that rode a handoff
        # Tiered-KV / QoS counters (zero when the features are off).
        self.kv_suspends = 0          # live streams parked to the host tier
        self.kv_resumes = 0           # parked streams re-admitted
        self.kv_host_hits = 0         # trie misses served by the host tier
        # Fleet KV-economy counters (zero without a directory/cold store).
        self.kv_peer_hits = 0         # prefixes imported from a peer replica
        self.kv_peer_misses = 0       # probes that found nothing importable
        self.kv_peer_import_bytes = 0  # payload bytes pulled from peers
        self.kv_peer_fetch_failures = 0  # dead holder / refused pull
        self.kv_cold_hits = 0         # prefixes imported from the cold store
        self.kv_cold_demotions = 0    # host evictions packed into cold
        self.kv_cold_import_bytes = 0  # payload bytes promoted from cold
        self.kv_import_stale_refused = 0  # envelopes refused: stale epoch
        self.kv_import_skipped_crossover = 0  # gains under the threshold
        self.kv_directory_publishes = 0  # holder hints this replica wrote
        self.qos_deadline_shed = 0    # requests shed past their deadline
        self.hol_bypasses = 0         # admissions that jumped a blocked head
        # Decode service per tenant (tokens emitted) — the weighted-fair
        # ordering's used-share input. Guarded by _mlock with the other
        # counters.
        self._tenant_served: dict[str, float] = {}
        self.kv_blocks_peak = 0      # high-water blocks_in_use
        self.peak_in_flight = 0      # high-water concurrent requests
        # Counter mutations and metrics() reads go through this lock so
        # derived ratios (ttft_avg_s, spec_acceptance_rate) are computed
        # from a CONSISTENT snapshot, never from a torn sum/count pair
        # mid-update. Leaf lock: never acquired while holding it.
        self._mlock = threading.Lock()
        # Latency *distributions* (the autoscaler/scheduler signals
        # averages can't carry): TTFT, inter-token gap, device dispatch
        # duration by kind, queue wait, and per-dispatch batch occupancy.
        # Rendered by the model server's /monitoring exposition; quantile
        # estimates surface in metrics() (p50/p90/p99).
        self.registry = MetricRegistry()
        self._h_ttft = self.registry.histogram(
            "serving_ttft_seconds", "Submit to first emitted token")
        self._h_itl = self.registry.histogram(
            "serving_inter_token_seconds",
            "Host-side gap between a stream's token arrivals")
        self._h_queue_wait = self.registry.histogram(
            "serving_queue_wait_seconds",
            "Submit to slot admission (includes memory deferrals)")
        # Per-tenant queue wait: tenant ids are hash-bucketed into a
        # BOUNDED label set (qos.tenant_bucket) — raw ids are
        # client-controlled and would explode exposition cardinality.
        self._h_tenant_wait = self.registry.histogram(
            "serving_tenant_queue_wait_seconds",
            "Submit to slot admission, by hash-bucketed tenant",
            labels=("tenant",))
        self._h_dispatch = self.registry.histogram(
            "serving_dispatch_seconds",
            "Device round-trip duration", labels=("kind",))
        occ_bounds, b = [], 1
        while b < slots:
            occ_bounds.append(b)
            b *= 2
        occ_bounds.append(slots)
        self._h_occupancy = self.registry.histogram(
            "serving_batch_occupancy",
            "Active slots per decode dispatch", buckets=occ_bounds)
        # Role label on the exposition so per-pool dashboards and the
        # operator's scrape can tell prefill from decode replicas
        # without inspecting Deployment names.
        self.registry.gauge(
            "serving_role",
            "Replica role in a disaggregated fleet (1 = this role)",
            labels=("role",)).labels(self.role or "colocated").set(1)
        self.registry.gauge(
            "serving_tp_shards",
            "Tensor-parallel mesh width of this replica (1 = "
            "single-chip)").set(self.tp_shards)
        self.registry.gauge(
            "serving_cp_shards",
            "Context-parallel (sequence-axis) width of this replica's "
            "chunked-prefill ring (1 = no ring)").set(self.cp_shards)
        self.registry.gauge(
            "serving_pp_stages",
            "Pipeline-parallel stages sharding this replica's layer "
            "stack and KV pool (1 = unsplit)").set(self.pp_stages)
        self._c_prefill_chunks = self.registry.counter(
            "serving_prefill_chunks_total",
            "Chunked-prefill dispatches (interior chunks of long "
            "admissions; the final chunk counts as a prefill)")
        self._h_prefill_chunk = self.registry.histogram(
            "serving_prefill_chunk_seconds",
            "Chunked-prefill dispatch duration (one interior chunk)")
        # Live weight streaming (update_weights): monotonically
        # increasing weights epoch, push counter, and the end-to-end
        # push duration (device placement + atomic swap + stale flush).
        # A peer-born replica stamps its donor's epoch at construction
        # (boot_weights_version) so the rollout machinery and the
        # stale-KV fences see a version-consistent fleet from birth.
        self.weights_version = max(0, int(boot_weights_version))
        self.weight_pushes = 0
        self.weight_stale_refused = 0  # stale trie/tier hits refused
        self.last_swap_seconds = 0.0   # last push's in-lock swap stall
        self._g_weights_version = self.registry.gauge(
            "serving_weights_version",
            "Weights epoch installed by live pushes (0 = boot weights)")
        if self.weights_version:
            self._g_weights_version.set(self.weights_version)
        self._c_weight_pushes = self.registry.counter(
            "serving_weight_pushes_total",
            "Live weight swaps installed by update_weights")
        self._h_weight_push = self.registry.histogram(
            "serving_weight_push_seconds",
            "update_weights duration: device placement, atomic swap, "
            "stale-KV flush")
        # Per-stream lifecycle timelines, bounded ring, served at the
        # model server's /debug/requests (JSON + chrome-trace export).
        self.trace = TraceStore()
        self._ramp_streak = 0  # consecutive admission-only rounds
        if self.prefix_cache is not None and self._alloc is not None:
            # Trie evictions must return the entry's refcounted blocks
            # to the pool; remove() fires this under the prefix lock.
            self.prefix_cache.on_evict = self._drop_entry_blocks
        if self._host_tier is not None:
            # Host-tier observability (the directory publish path must
            # be visible to size the tier): eviction-age distribution;
            # occupancy/high-water ride metrics() gauges.
            self._h_host_evict_age = self.registry.histogram(
                "serving_kv_host_eviction_age_seconds",
                "Idle time a demoted payload sat in the host tier "
                "before LRU pressure evicted it")
            self._host_tier.eviction_age_observe = \
                self._h_host_evict_age.observe
            if self.cold_store is not None:
                # The economy's demotion chain: host-tier evictions
                # pack into the cold store (and publish the hint)
                # BEFORE the bytes drop.
                self._host_tier.on_evict = self._demote_to_cold
        # Newborn ramp state: a birth path (model server boot, fleet
        # add_replica) sets `warming` True before calling warm(); the
        # fleet admits a warming member via least-loaded spill only —
        # no affine share — and /healthz reports "warming" so the
        # gateway excludes it without penalty. Defaults False: a
        # decoder constructed outside a birth path serves immediately.
        self.warming = False
        self.compile_cache_hits = 0
        self.compile_cache_misses = 0
        self.warm_seconds = 0.0
        self.compile_cache = None
        if compile_cache_dir:
            from kubeflow_tpu.serving.compile_cache import CompileCache
            self.compile_cache = CompileCache(compile_cache_dir)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------

    def engine_fingerprint(self) -> str:
        """Digest keying this decoder's compiled dispatch set in the
        persistent compile cache (see serving/compile_cache.py)."""
        from kubeflow_tpu.serving.compile_cache import engine_fingerprint
        return engine_fingerprint(
            self.cfg, tp_shards=self.tp_shards, cp_shards=self.cp_shards,
            pp_stages=self.pp_stages, kv_layout=self.kv_layout,
            kv_dtype=self.kv_dtype, kv_fused=self.kv_fused,
            kv_block_size=getattr(self, "kv_block_size", 0),
            slots=self.slots, prefill_len=self.prefill_len,
            prefill_len_buckets=self.prefill_len_buckets,
            chunk_size=self.chunk_size,
            speculative_k=self.speculative_k,
            prefill_chunk_tokens=self.prefill_chunk_tokens,
            max_prompt_len=self.max_prompt_len, top_k=self.top_k)

    def dispatch_keys(self) -> list[str]:
        from kubeflow_tpu.serving.compile_cache import dispatch_keys
        return dispatch_keys(
            slots=self.slots, prefill_len=self.prefill_len,
            prefill_len_buckets=self.prefill_len_buckets,
            chunk_size=self.chunk_size,
            speculative_k=self.speculative_k,
            prefill_chunk_tokens=self.prefill_chunk_tokens)

    def warm(self, compile_cache=None) -> dict:
        """Pre-compile the full dispatch set by running dummy
        generations through the real submit path — one admission per
        prefill bucket, decode steps at the chunk width, the verify
        shape under speculation, and the chunked-prefill interior shape
        for long prompts. Populates the in-process jit cache and (when
        wired) XLA's persistent store; the manifest accounting splits
        the set into hits (a prior same-fingerprint replica already
        compiled them — this birth deserializes) vs misses (compiled
        here, recorded for the next birth). Flips ``warming`` off at
        the end — the fleet/gateway ramp gate.

        Never raises: a newborn that cannot warm one shape (QoS rate
        limit on the dummy tenant, a bucket wider than max_prompt_len)
        still comes up and compiles that shape on first real traffic.
        """
        t0 = time.perf_counter()
        cache = compile_cache if compile_cache is not None \
            else self.compile_cache
        floor = (self.prefill_len >> self.prefill_len_buckets
                 if self.prefill_len_buckets else self.prefill_len)
        widths, w = [], max(1, floor)
        while True:
            widths.append(w)
            if w >= self.prefill_len:
                break
            w *= 2
        # Distinctive token pattern: repeated so the ngram proposer
        # drafts (driving the verify executable), and unlikely to alias
        # real prompts in the prefix trie.
        handles = []
        steps = max(1, min(self.max_new_tokens, self.chunk_size))
        for w in widths:
            n = max(1, min(w, self.max_prompt_len))
            prompt = ([7, 11, 13] * (n // 3 + 1))[:n]
            try:
                handles.append(self.submit(prompt, steps))
            except Exception:
                continue
        if self.prefill_chunk_tokens and self.max_prompt_len \
                > self.prefill_len:
            n = min(self.max_prompt_len,
                    self.prefill_len + self.prefill_chunk_tokens)
            prompt = ([7, 11, 13] * (n // 3 + 1))[:n]
            try:
                handles.append(self.submit(prompt, steps))
            except Exception:
                pass
        for h in handles:
            try:
                h.result()
            except Exception:
                continue
        hits = misses = 0
        if cache is not None:
            hits, misses = cache.account(self.engine_fingerprint(),
                                         self.dispatch_keys())
        secs = time.perf_counter() - t0
        with self._mlock:
            self.compile_cache_hits += hits
            self.compile_cache_misses += misses
            self.warm_seconds = secs
        self.warming = False
        return {"seconds": secs, "hits": hits, "misses": misses,
                "keys": len(self.dispatch_keys())}

    def weights_snapshot(self):
        """Consistent (params, weights_version) pair for a donor-side
        peer pull: pointer reads under the state lock (no copies, no
        blocking work) — the same discipline update_weights' swap uses,
        so a puller never sees epoch N's version with epoch N+1's
        pytree."""
        with self._state_lock:
            return self.params, self.weights_version

    def submit(self, tokens: list[int], max_new_tokens: int,
               temperature: float = 0.0, *,
               request_id: str | None = None, tenant: str = "",
               priority: int | None = None,
               deadline_ms: float = 0.0) -> StreamHandle:
        """``tenant``/``priority``/``deadline_ms`` are the QoS surface
        (threaded from the gateway's X-Tenant/X-Priority/X-Deadline-Ms
        headers). With a QoS policy configured, the tenant's token
        bucket gates this call (raises
        :class:`~kubeflow_tpu.serving.qos.QosRejected` -> HTTP 429 with
        Retry-After), the pop loop orders by weighted fair share +
        aged priority, and a request still queued past its deadline is
        shed instead of served."""
        if self.qos is not None:
            # Raises QosRejected when the tenant's bucket is empty —
            # BEFORE the request enters the queue, so overload degrades
            # to fast 429s instead of queue collapse.
            self.qos.admit(tenant, time.perf_counter())
        if len(tokens) > self.max_prompt_len:
            # Terminal, not truncation: silently dropping the prompt
            # tail would serve an answer to a question the caller never
            # asked. max_prompt_len is the replica's hard ceiling
            # (chunking already lifted it past the compiled prefill
            # width) — beyond it the request is a 413, like any body
            # the server cannot represent.
            with self._mlock:
                self.prompt_rejected_too_long += 1
            raise PromptTooLong(
                f"prompt is {len(tokens)} tokens but this replica "
                f"serves at most {self.max_prompt_len} "
                f"(max_prompt_len; prefill_chunk_tokens="
                f"{self.prefill_chunk_tokens})")
        req = _Request(tokens=list(tokens),
                       want=min(max_new_tokens, self.max_new_tokens),
                       temperature=float(temperature))
        req.tenant = tenant or DEFAULT_TENANT
        req.priority = (self.qos.base_priority(tenant, priority)
                        if self.qos is not None else int(priority or 0))
        if deadline_ms and deadline_ms > 0:
            req.deadline_t = req.submit_t + float(deadline_ms) / 1e3
        # Lifecycle timeline, keyed by the propagated X-Request-ID (or a
        # fresh one): submit marks t=0, queued marks entry to the pending
        # deque — every later phase hangs off these two anchors.
        req.timeline = self.trace.start(request_id)
        req.request_id = req.timeline.request_id
        req.timeline.event("submit", prompt_tokens=len(req.tokens),
                           want=req.want, tenant=req.tenant,
                           priority=req.priority)
        if self.kv_directory is not None or self.cold_store is not None:
            # Fleet miss-path probe (trie -> host -> peer -> cold) on
            # the CALLER's thread, before the request enters the queue:
            # the pop loop plans prefixes under the scheduler condition,
            # where a blocking peer fetch would stall every submit. A
            # successful import lands in the trie, so pop-time planning
            # sees an ordinary local hit. Probes are best-effort — an
            # import failure must never fail the submit it was trying
            # to speed up.
            try:
                self._maybe_import_remote(req.tokens, req.timeline)
            except Exception:
                pass
        with self._cv:
            if self._stopped:
                req.timeline.close(error=RuntimeError("decoder is stopped"))
                raise RuntimeError("decoder is stopped")
            self._pending.append(req)
            req.timeline.event("queued", depth=len(self._pending))
            self._cv.notify()
        return StreamHandle(req, self.stream_timeout_s)

    def generate(self, tokens: list[int], max_new_tokens: int,
                 temperature: float = 0.0,
                 timeout: float | None = None, **submit_kw) -> dict:
        return self.submit(tokens, max_new_tokens, temperature,
                           **submit_kw).result(timeout)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            # Snapshot under the cv: the scheduler thread may be
            # mid-pop, and join() below can time out — after which
            # iterating the live deque would race its popleft.
            queued = list(self._pending)
            self._cv.notify()
        self._thread.join(timeout=5)
        err = RuntimeError("decoder stopped")
        for req in queued + self._slot_req:
            if req is not None and not req.done.is_set():
                self._finish(req, error=err)

    # ------------------------------------------------------------------

    def _finish(self, req: _Request, *, reason: str = "length",
                error: Exception | None = None) -> None:
        # Idempotent: the crash path (_fail_all) sweeps everything still
        # live on loop exit, racing stop() and the inner error handler —
        # first finisher wins, later calls are no-ops.
        if req.done.is_set():
            return
        # A suspended request dying (deadline shed, stop, loop death)
        # must drain its pinned host-tier payload — pinned bytes are
        # exempt from LRU pressure, so nothing else ever reclaims them.
        if req.host_key is not None and self._host_tier is not None:
            with self._prefix_lock:
                self._host_tier.discard(req.host_key)
            req.host_key = None
        req.error = error
        req.finish_reason = reason if error is None else "error"
        if req.timeline is not None:
            # Every finish path funnels here, so a closed request can
            # never leak an open timeline — the invariant the chaos
            # (_fail_all) test pins.
            req.timeline.close(req.finish_reason, error=error)
        req.stream.put(_DONE)
        req.done.set()

    # -- paged-KV bookkeeping (no-ops in the dense layout) -------------

    def _drop_entry_blocks(self, entry) -> None:
        """Prefix-trie eviction hook: DEMOTE the entry's blocks to the
        host tier (HBM -> host, verbatim bytes), then release the
        refcounted blocks. Called by PrefixCache.remove() with the
        prefix lock held — must not re-acquire it."""
        if self._host_tier is not None and entry.blocks:
            self._demote_entry(entry)
        for b in (entry.blocks or ()):
            self._alloc.free(b)

    def _demote_entry(self, entry) -> None:
        """Export an evicted entry's blocks into the host tier so a
        later miss gets a second chance instead of a cold prefill.
        Runs under the prefix lock (the eviction path itself); the
        export's device fetch MUST complete before the blocks return
        to the free list below us, so this is the one spot the
        eviction path pays a device round-trip — the price of
        demoting instead of destroying."""
        # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; swap flush re-sweeps
        if entry.version != self.weights_version:
            return  # stale epoch: destroying beats a poisoned second chance
        plen = min(len(entry.key), len(entry.blocks) * self.kv_block_size)
        key = tuple(entry.key[:plen])
        if plen < 1 or self._host_tier.has(key):
            return
        est = (self._alloc.blocks_for(plen) * self.kv_block_size
               * self._host_bytes_per_token)
        if not self._host_tier.can_fit(est):
            return  # pinned suspensions own the budget; skip the copy
        ids = list(entry.blocks[: self._alloc.blocks_for(plen)])
        try:
            payload = self._export_ids(ids)
        except Exception:
            # A dead/poisoned device state must not wedge the eviction
            # path (the crash drain evicts the whole trie): losing the
            # second-chance copy is fine, losing the free() is a leak.
            return
        if self._host_tier.put(key, payload, plen,
                               version=entry.version):
            self._publish_directory(key, plen, entry.version,
                                    tier="host")

    def _set_table_row(self, slot: int, blocks: list[int]) -> None:
        """Point ``slot``'s host block-table row at ``blocks`` (sentinel
        beyond them); uploaded to device at the next admission call."""
        self._table[slot, :] = self._alloc.num_blocks
        self._table[slot, : len(blocks)] = blocks

    def _free_slot_blocks(self, slot: int) -> None:
        """Return a retiring slot's block references to the allocator.
        Idempotent — the crash path can race the normal finish path, and
        only the first call finds blocks to free."""
        if self._alloc is None:
            return
        with self._prefix_lock:
            # tpu-lint: disable=lock-inconsistent-guard -- scheduler-thread-owned slot state
            blocks, self._slot_blocks[slot] = self._slot_blocks[slot], []
            for b in blocks:
                self._alloc.free(b)
            if blocks:
                # tpu-lint: disable=lock-inconsistent-guard -- row arms under own dispatch (PR-8)
                self._table[slot, :] = self._alloc.num_blocks

    def _reclaim_blocks(self, need: int, timeline=None) -> None:
        """Evict unpinned prefix-cache entries (LRU first) until ``need``
        blocks are free — cache-held blocks are reclaimable memory, not
        reservations, so admission pressure beats cold cache entries.
        Caller holds the prefix lock. Evictions forced by an admission
        land on that request's timeline."""
        if self.prefix_cache is None:
            return
        evicted = 0
        while self._alloc.free_blocks < need:
            if not self.prefix_cache.evict_lru():
                break
            evicted += 1
        if evicted and timeline is not None:
            timeline.event("kv_evict", entries=evicted)

    def _admit_batch(self, pending: list[tuple[_Request, int]]) -> None:
        """Admit a round's pending requests in ONE dispatch that fuses
        prefill, state insert, AND one decode step
        (:func:`admit_rows_and_step`) — the new requests' first token
        ships on the admission round-trip itself.

        The batch is padded up to a power-of-two bucket in BOTH
        dimensions (bounding the number of compiled prefill shapes):
        batch rows by repeating the last real admission verbatim
        (duplicate scatter indices with identical payloads are
        deterministic, so padding is a no-op re-write), and — with
        ``prefill_len_buckets`` — the sequence dim to the smallest
        allowed power of two covering the round's longest prompt, so
        short prompts ride short executables instead of paying
        full-``prefill_len`` prefill compute.
        """
        k = len(pending)
        bucket = pow2_bucket(k)
        t = self._seq_bucket(max(len(req.tokens) for req, _ in pending))
        toks = np.zeros((bucket, t), np.int32)
        lengths = np.ones((bucket,), np.int32)
        slots = np.zeros((bucket,), np.int32)
        temps = np.zeros((bucket,), np.float32)
        wants = np.zeros((bucket,), np.int32)
        for i in range(bucket):
            req, slot = pending[min(i, k - 1)]  # pad = repeat last real
            toks[i, : len(req.tokens)] = req.tokens
            lengths[i] = max(len(req.tokens), 1)
            slots[i] = slot
            temps[i] = req.temperature
            wants[i] = req.want_left
        # ONE admission executable per (batch, length) bucket: always the
        # fused variant (the extra decode step is ~free on device, and a
        # second plain-admit executable would surprise-compile
        # mid-traffic). The paged twin reads each slot's block-table row
        # (allocated at pop time) instead of scattering into dense rows.
        t_disp = time.perf_counter()
        with self._state_lock:
            # The weights epoch this admission's prefill runs under —
            # read inside the same lock scope that passes self.params
            # to the dispatch, so it can never stamp the wrong epoch.
            for req, _slot in pending:
                req.weights_version = self.weights_version
            if self._alloc is not None:
                # Table rows go live only now, under THIS dispatch —
                # the rows' device length/active are set by the same
                # call, so no other dispatch can ever write through a
                # freshly mapped row with a stale length.
                for req, slot in pending:
                    self._set_table_row(slot, self._slot_blocks[slot])
                self._state["block_table"] = jnp.asarray(self._table)
                self._state, last, tok, emit = paged_admit_rows_and_step(
                    self._state, self.params, self.cfg,
                    jnp.asarray(slots), jnp.asarray(toks),
                    jnp.asarray(lengths), jnp.asarray(wants),
                    jnp.asarray(temps), self.top_k, self.eos_id,
                    self.kv_fused, self._kmesh)
            else:
                self._state, last, tok, emit = admit_rows_and_step(
                    self._state, self.params, self.cfg,
                    jnp.asarray(slots), jnp.asarray(toks),
                    jnp.asarray(lengths), jnp.asarray(wants),
                    jnp.asarray(temps), self.top_k, self.eos_id)
        with self._mlock:
            self.prefill_dispatches += 1
            self.admitted += k
            self.prefill_tokens += sum(len(req.tokens)
                                       for req, _ in pending)
        # Fetch ONLY the fused step's tokens (one small transfer);
        # vocab-wide prefill logits stay on device behind a lazy
        # per-request resolver — eager [K, V] fetches each admission
        # round cost more tunnel time than the decode itself.
        tok_np, emit_np = jax.device_get((tok, emit))
        self._h_dispatch.labels("admit").observe(
            time.perf_counter() - t_disp)
        for i, (req, slot) in enumerate(pending):
            req.prefill_src = (last, i)
            if req.timeline is not None:
                req.timeline.event("prefill", tokens=len(req.tokens),
                                   bucket=t)
            self._post_admit(req, slot)
        # The fused decode step's tokens (new rows' first token AND
        # every peer row's next token) — routed after _post_admit so
        # the new rows are registered.
        with self._mlock:
            self.steps += 1
        self._dispatch(tok_np, emit_np)

    def _seq_bucket(self, n: int) -> int:
        """Compiled prefill length for an ``n``-token prompt."""
        if self.prefill_len_buckets <= 0:
            return self.prefill_len
        floor = max(1, self.prefill_len >> self.prefill_len_buckets)
        return pow2_bucket(max(n, floor), cap=self.prefill_len)

    def _suffix_bucket(self, n: int) -> int:
        """Compiled suffix length for prefix-hit admissions. Suffixes are
        bucketed even when full-prompt bucketing is off — padding a
        3-token suffix to ``prefill_len`` would erase the reuse win —
        with a floor bounding the executable count."""
        if self.prefill_len_buckets > 0:
            floor = max(1, self.prefill_len >> self.prefill_len_buckets)
        else:
            floor = min(8, self.prefill_len)
        return pow2_bucket(max(n, floor), cap=self.prefill_len)

    def _plan_prefix(self, req: _Request):
        """Probe the trie for ``req`` and fit the (prefix, suffix-bucket)
        split into the cache: the suffix block must end within
        ``total_len`` (an out-of-bounds ``dynamic_update_slice`` start
        would be CLAMPED by XLA and silently corrupt the row), so when
        the bucket rounds past the prompt's tail the reused prefix is
        shortened to ``prompt_len - bucket`` — less reuse, never a wrong
        write. Returns (entry, prefix_len, bucket) with the entry pinned,
        or None (miss; any pin released)."""
        # A resumed (previously suspended) stream may consume K/V from
        # the epoch it was parked under — the payload IS its state and
        # the stream straddles the swap by design. Fresh requests must
        # only ever hit the live epoch.
        allow_stale = bool(req.out or req.folded)
        with self._prefix_lock:
            m = self.prefix_cache.match(req.tokens)
            # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; publish guard catches
            live_epoch = self.weights_version
            if (m is not None and not allow_stale
                    and m[0].version != live_epoch):
                # Stale hit: refuse, and remove the entry so it stops
                # shadowing deeper fresh entries (pinned peers keep it
                # alive until their release; it stays refused).
                entry = m[0]
                self.prefix_cache.release(entry)
                if entry.refs == 0:
                    self.prefix_cache.remove(entry)
                with self._mlock:
                    self.weight_stale_refused += 1
                m = None
        if m is None and self._host_tier is not None \
                and self._alloc is not None:
            # Second chance: a demoted (or suspended) prefix in the
            # host tier re-imports onto device and the admission
            # proceeds as an ordinary prefix hit.
            if self._promote_host_prefix(req.tokens, req.timeline,
                                         allow_stale=allow_stale):
                with self._prefix_lock:
                    m = self.prefix_cache.match(req.tokens)
        if m is None:
            return None
        entry, plen = m
        n = len(req.tokens)
        s = self._suffix_bucket(n - plen)
        if plen + s > self.total_len:
            plen = n - s
        if s >= n or plen < self.prefix_cache.min_len:
            # Too little left to reuse once bucketed — full prefill wins.
            with self._prefix_lock:
                self.prefix_cache.release(entry)
            return None
        return entry, plen, s

    def _admit_prefix(self, req: _Request, slot: int, entry,
                      prefix_len: int, s: int) -> None:
        """Prefix-hit admission: ONE dispatch gathers the cached K/V rows
        into the request's row, prefills only the suffix (padded to the
        ``s`` length bucket), and takes the fused decode step — so a
        prompt whose first ``prefix_len`` tokens are pooled pays
        suffix-sized prefill compute. ``entry`` arrives pinned
        (match() refcounted it) and stays pinned until the request
        finishes."""
        suffix = req.tokens[prefix_len:]
        toks = np.zeros((1, s), np.int32)
        toks[0, : len(suffix)] = suffix
        t_disp = time.perf_counter()
        if self._alloc is not None:
            # The pop-time reservation already mapped the donor's FULL
            # prefix blocks into this slot by refcount — zero device
            # copies. Here only a partially-filled tail block pays its
            # CoW (one block copy), then the suffix prefill reads the
            # shared prefix in place through the block table.
            bs = self.kv_block_size
            n_full = prefix_len // bs
            with self._state_lock:
                req.weights_version = self.weights_version
                if prefix_len % bs:
                    # First owned block (table index n_full) receives
                    # the donor's partially-shared tail content.
                    self._state["pool"] = copy_block(
                        self._state["pool"],
                        jnp.int32(self._slot_blocks[slot][n_full]),
                        jnp.int32(entry.blocks[n_full]))
                # Map the slot's table row only under its own dispatch
                # (see the pop loop: a row live before its admission is
                # a stale-length write hazard into shared blocks).
                self._set_table_row(slot, self._slot_blocks[slot])
                self._state["block_table"] = jnp.asarray(self._table)
                self._state, last, tok, emit = paged_admit_prefix_and_step(
                    self._state, self.params, self.cfg, jnp.int32(slot),
                    jnp.int32(prefix_len), jnp.asarray(toks),
                    jnp.int32(len(req.tokens)),
                    jnp.int32(req.want_left),
                    jnp.float32(req.temperature), self.top_k, self.eos_id,
                    self.kv_fused, self._kmesh)
            with self._mlock:
                self.kv_shared_blocks += n_full
                if prefix_len % bs:
                    self.kv_cow_copies += 1
        else:
            with self._prefix_lock:
                pool = self._prefix_pool
            with self._state_lock:
                req.weights_version = self.weights_version
                self._state, last, tok, emit = admit_prefix_and_step(
                    self._state, self.params, self.cfg, jnp.int32(slot),
                    pool, jnp.int32(entry.slot), jnp.int32(prefix_len),
                    jnp.asarray(toks), jnp.int32(len(req.tokens)),
                    jnp.int32(req.want_left),
                    jnp.float32(req.temperature),
                    self.top_k, self.eos_id)
        req.pinned_prefix = entry
        with self._mlock:
            self.prefill_dispatches += 1
            self.admitted += 1
            self.prefix_hits += 1
            self.prefix_tokens_reused += prefix_len
            self.prefix_suffix_tokens += len(suffix)
            self.prefill_tokens += len(suffix)
        tok_np, emit_np = jax.device_get((tok, emit))
        self._h_dispatch.labels("admit").observe(
            time.perf_counter() - t_disp)
        req.prefill_src = (last, 0)
        if req.timeline is not None:
            req.timeline.event("prefill", tokens=len(suffix),
                               prefix_reused=prefix_len, bucket=s)
        self._post_admit(req, slot)
        with self._mlock:
            self.steps += 1
        self._dispatch(tok_np, emit_np)

    def _begin_chunked(self, req: _Request, slot: int) -> None:
        """Register a long admission as a chunk job. The slot and its
        block reservation are taken NOW (pop time already reserved the
        blocks; a prefix plan already pinned its entry), but no device
        work runs here — the pop loop advances the chain one bounded
        chunk per round via :meth:`_advance_chunked`, interleaved with
        decode dispatches. The slot counts as OCCUPIED (no other
        admission can take it) but not ACTIVE (its row is parked;
        decode rounds don't feed it)."""
        plan = req.admit_plan
        plen = plan[1] if plan is not None else 0
        if plan is not None:
            req.pinned_prefix = plan[0]
            with self._mlock:
                self.prefix_hits += 1
                self.prefix_tokens_reused += plen
                self.prefix_suffix_tokens += len(req.tokens) - plen
                self.kv_shared_blocks += plen // self.kv_block_size
        elif self.prefix_cache is not None:
            with self._mlock:
                self.prefix_misses += 1
        req.chunk_pos = plen
        req.chunk_started = False
        self._slot_req[slot] = req
        self._chunk_jobs.append((req, slot))
        if req.timeline is not None:
            req.timeline.event("chunked_admission",
                               prompt_tokens=len(req.tokens),
                               prefix_reused=plen,
                               chunk_tokens=self.prefill_chunk_tokens)

    def _advance_chunked(self) -> None:
        """Run AT MOST ONE chunk dispatch — the oldest job's next chunk.
        One chunk per round is the interleave that bounds a live
        stream's inter-token gap at one chunk of prefill compute.

        Interior chunks scatter ``prefill_chunk_tokens`` prompt tokens
        into the row's blocks and re-park the row (no sampling, no RNG
        consumed — the chain stays byte-identical to a monolithic
        prefill because K/V bytes depend only on token values and
        positions). The FINAL chunk is an ordinary prefix-style
        admission with ``prefix_len = chunk_pos``: it activates the row,
        samples the first token, and fuses the round's decode step —
        exactly the pinned prefix-hit path, so the chain ends in the
        same dispatch shape a cache hit uses."""
        if not self._chunk_jobs:
            return
        req, slot = self._chunk_jobs[0]
        n = len(req.tokens)
        pos = req.chunk_pos
        remaining = n - pos
        final = remaining <= self.prefill_chunk_tokens
        take = remaining if final else self.prefill_chunk_tokens
        s = self._suffix_bucket(take)
        toks = np.zeros((1, s), np.int32)
        toks[0, :take] = req.tokens[pos: pos + take]
        first = not req.chunk_started
        plan = req.admit_plan
        bs = self.kv_block_size
        restart = False
        t_disp = time.perf_counter()
        with self._state_lock:
            if first:
                # First chunk: stamp the weights epoch, CoW the plan's
                # partially-shared tail block, and map the table row —
                # all inside this dispatch's lock scope, mirroring
                # _admit_prefix (the stale-row discipline: the row
                # exists on device only once its own chain writes it).
                req.chunk_started = True
                req.weights_version = self.weights_version
                if plan is not None and plan[1] % bs:
                    n_full = plan[1] // bs
                    self._state["pool"] = copy_block(
                        self._state["pool"],
                        jnp.int32(self._slot_blocks[slot][n_full]),
                        jnp.int32(plan[0].blocks[n_full]))
                self._set_table_row(slot, self._slot_blocks[slot])
                self._state["block_table"] = jnp.asarray(self._table)
            elif req.weights_version != self.weights_version:
                # A live weight swap landed mid-chain: blocks written so
                # far are old-epoch, the rest would be new-epoch — one
                # row must never mix epochs (the trie would republish
                # the mixture). Abort below, outside the lock.
                restart = True
            if not restart:
                if final:
                    self._state, last, tok, emit = \
                        paged_admit_prefix_and_step(
                            self._state, self.params, self.cfg,
                            jnp.int32(slot), jnp.int32(pos),
                            jnp.asarray(toks), jnp.int32(n),
                            jnp.int32(req.want_left),
                            jnp.float32(req.temperature), self.top_k,
                            self.eos_id, self.kv_fused, self._kmesh,
                            ring=self._ring)
                else:
                    self._state = paged_prefill_chunk(
                        self._state, self.params, self.cfg,
                        jnp.int32(slot), jnp.int32(pos),
                        jnp.asarray(toks), jnp.int32(take),
                        self.kv_fused, self._kmesh, ring=self._ring)
        if restart:
            self._restart_chunked(req, slot)
            return
        if first and plan is not None and plan[1] % bs:
            with self._mlock:
                self.kv_cow_copies += 1
        dt = time.perf_counter() - t_disp
        if not final:
            req.chunk_pos = pos + take
            with self._mlock:
                self.prefill_chunks += 1
                self.prefill_tokens += take
            self._c_prefill_chunks.inc()
            self._h_prefill_chunk.observe(dt)
            self._h_dispatch.labels("prefill_chunk").observe(dt)
            if req.timeline is not None:
                req.timeline.event("prefill_chunk", pos=pos, tokens=take,
                                   bucket=s)
            return
        # Final chunk: the chain is done — promote to an ordinary
        # admitted stream (the fused step's token dispatches below).
        self._chunk_jobs.pop(0)
        with self._mlock:
            self.prefill_dispatches += 1
            self.admitted += 1
            self.prefill_tokens += take
        tok_np, emit_np = jax.device_get((tok, emit))
        self._h_dispatch.labels("admit").observe(dt)
        req.prefill_src = (last, 0)
        if req.timeline is not None:
            req.timeline.event("prefill", tokens=take, prefix_reused=pos,
                               bucket=s, chunked=True)
        req.chunk_pos = -1
        self._post_admit(req, slot)
        with self._mlock:
            self.steps += 1
        self._dispatch(tok_np, emit_np)

    def _restart_chunked(self, req: _Request, slot: int) -> None:
        """Abort a mid-chain chunked admission and replay it from the
        queue. The whole chain restarts under the new weights epoch
        (the repop replans prefix reuse against the post-swap trie), so
        a chunked stream — like every other stream — is consistent with
        exactly one weights version, never an interleave. Swaps are
        rare relative to chain length, so the replay cost is noise and
        livelock is not a concern."""
        self._chunk_jobs.pop(0)
        self._slot_req[slot] = None
        self._release_pin(req)
        self._free_slot_blocks(slot)
        req.admit_plan = None
        req.chunk_pos = -1
        req.chunk_started = False
        if req.timeline is not None:
            req.timeline.event("chunk_restart", reason="weight_swap")
        with self._cv:
            if self._stopped:
                self._finish(req, error=RuntimeError("decoder stopped"))
                return
            self._pending.appendleft(req)
            self._cv.notify()

    def _publish_prefix(self, req: _Request, slot: int) -> None:
        """Publish a finishing request's prompt K/V (still intact in its
        row's cache positions 0..len-1) into the prefix pool, so later
        prompts sharing the prefix skip its prefill. Runs on the
        scheduler thread BEFORE the slot is freed."""
        cache = self.prefix_cache
        if cache is None or req.error is not None:
            return
        # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; swap flush removes it
        if req.weights_version != self.weights_version:
            # The stream straddled a live weight swap: its prompt K/V
            # was computed under weights the decoder no longer serves —
            # pooling it would hand stale bytes to post-swap admissions.
            return
        ent = req.pinned_prefix
        if ent is not None and getattr(ent, "version", 0) != \
                req.weights_version:
            # Plan/admit race across a swap: the prefix plan pinned a
            # then-current entry, the swap landed before the admission
            # dispatch, and the row's leading K/V is old-epoch while
            # its suffix is new. The stream itself is a legal straddler
            # (one version boundary), but its blocks must never enter
            # the trie stamped as the new epoch.
            return
        key = tuple(req.tokens)
        if len(key) < cache.min_len:
            return
        with self._prefix_lock:
            if cache.has(key):
                cache.touch(key)
                return
            entry = cache.reserve(key)
            if entry is None:  # every pool slot pinned by peers in flight
                return
            entry.version = req.weights_version
            if self._alloc is not None:
                # Paged publish is pure bookkeeping: the prompt's K/V
                # already lives in the slot's pool blocks, so the entry
                # just takes a reference on the blocks covering the key
                # (they outlive the slot's own release). ZERO copies.
                n_pub = min(self._alloc.blocks_for(len(key)),
                            len(self._slot_blocks[slot]))
                blocks = tuple(self._slot_blocks[slot][:n_pub])
                for b in blocks:
                    self._alloc.share(b)
                entry.blocks = blocks
            else:
                self._prefix_pool = store_prefix_row(
                    self._prefix_pool, jnp.int32(entry.slot),
                    # tpu-lint: disable=lock-inconsistent-guard -- dense _state scheduler-confined
                    self._state,
                    jnp.int32(slot))
            with self._mlock:
                self.prefix_inserts += 1
            plen = len(key)
            if entry.blocks:
                plen = min(plen, len(entry.blocks) * self.kv_block_size)
            self._publish_directory(key, plen, req.weights_version,
                                    tier="hbm")

    def _release_pin(self, req: _Request) -> None:
        if req.pinned_prefix is not None and self.prefix_cache is not None:
            with self._prefix_lock:
                self.prefix_cache.release(req.pinned_prefix)
            req.pinned_prefix = None

    def prime_prefix(self, tokens: list[int]) -> bool:
        """Precompute and pool a prefix (e.g. the shared system prompt at
        server start) WITHOUT touching the decode state or its RNG — a
        primed decoder samples byte-identically to an unprimed one.
        Returns True when the prefix is pooled (already or now)."""
        if self.prefix_cache is None:
            return False
        toks = list(tokens)[: self.prefill_len]
        if len(toks) < self.prefix_cache.min_len:
            return False
        key = tuple(toks)
        # One consistent (params, epoch) pair: a concurrent live weight
        # swap flips both under the state lock, and the primed entry's
        # version stamp must match the weights that computed its bytes.
        with self._state_lock:
            params, wver = self.params, self.weights_version
        with self._prefix_lock:
            if self.prefix_cache.has(key):
                self.prefix_cache.touch(key)
                return True
            entry = self.prefix_cache.reserve(key)
            if entry is None:
                return False
            if self._alloc is not None:
                # Paged prime: prefill into freshly allocated pool
                # blocks owned by the trie entry itself (refcount 1,
                # released on eviction). The state lock serializes the
                # pool write against the scheduler's donated steps.
                nblk = self._alloc.blocks_for(len(toks))
                self._reclaim_blocks(nblk)
                if not self._alloc.can_alloc(nblk):
                    self.prefix_cache.remove(entry)
                    return False
                blocks = self._alloc.alloc(nblk)
                self.kv_blocks_peak = max(self.kv_blocks_peak,
                                          self._alloc.blocks_in_use)
                try:
                    w = nblk * self.kv_block_size
                    arr = np.zeros((1, w), np.int32)
                    arr[0, : len(toks)] = toks
                    cache, _last = prefill(
                        params, jnp.asarray(arr),
                        jnp.asarray([len(toks)], np.int32), self.cfg,
                        total_len=w)
                    with self._state_lock:
                        self._state["pool"] = store_blocks(
                            self._state["pool"],
                            jnp.asarray(blocks, np.int32), cache)
                except Exception:
                    for b in blocks:
                        self._alloc.free(b)
                    self.prefix_cache.remove(entry)
                    raise
                entry.blocks = tuple(blocks)
            else:
                try:
                    t = self._seq_bucket(len(toks))
                    arr = np.zeros((1, t), np.int32)
                    arr[0, : len(toks)] = toks
                    cache, _last = prefill(
                        params, jnp.asarray(arr),
                        jnp.asarray([len(toks)], np.int32), self.cfg,
                        total_len=self.prefill_len)
                    self._prefix_pool = store_prefix_cache(
                        self._prefix_pool, jnp.int32(entry.slot), cache)
                except Exception:
                    self.prefix_cache.remove(entry)
                    raise
            entry.version = wver
            with self._mlock:
                self.prefix_inserts += 1
                self.prefill_tokens += len(toks)  # priming IS a prefill
            return True

    # -- disaggregated prefill/decode handoff --------------------------

    @staticmethod
    def _payload_nblk(payload: dict) -> int:
        """Block count a handoff payload carries (fp arrays and int8
        {"q","scale"} dicts share the [L, nblk, ...] leading layout)."""
        k = payload["k"]
        arr = k["q"] if isinstance(k, dict) else k
        return int(arr.shape[1])

    def _export_ids(self, ids: list[int]) -> dict:
        """Fetch pool blocks ``ids`` to the host as a handoff payload.
        The gather is padded to a power-of-two block count (repeating
        the last id — duplicate reads are free) so the number of
        compiled export shapes stays logarithmic, then trimmed."""
        nblk = len(ids)
        padded = ids + [ids[-1]] * (pow2_bucket(nblk) - nblk)
        # Dispatch the gather under the state lock, but fetch OUTSIDE
        # it: device_get blocks the host for the whole device→host
        # payload copy, and holding the state lock across that wait
        # would stall the scheduler's pop path for every export — the
        # same PR-9 stall class the import path already avoids. The
        # gather's result buffers are ours alone, so the fetch needs no
        # lock. (Surfaced by tpu-lint lock-blocking-call.)
        with self._state_lock:
            out_dev = export_blocks(
                self._state["pool"], jnp.asarray(padded, np.int32))
        out = jax.device_get(out_dev)

        def _trim(node):
            if isinstance(node, dict):
                return {k: v[:, :nblk] for k, v in node.items()}
            return node[:, :nblk]

        return {side: _trim(out[side]) for side in ("k", "v")}

    def _export_cold(self, prefix_toks: list[int]) -> dict:
        """Cache-less export source: prefill the prefix into scratch
        blocks, export them, free them — nothing outlives the call."""
        nblk = self._alloc.blocks_for(len(prefix_toks))
        with self._prefix_lock:
            self._reclaim_blocks(nblk)
            if not self._alloc.can_alloc(nblk):
                raise ValueError(
                    f"prompt export needs {nblk} free KV blocks; "
                    f"{self._alloc.free_blocks} available")
            blocks = self._alloc.alloc(nblk)
            self.kv_blocks_peak = max(self.kv_blocks_peak,
                                      self._alloc.blocks_in_use)
        try:
            w = nblk * self.kv_block_size
            arr = np.zeros((1, w), np.int32)
            arr[0, : len(prefix_toks)] = prefix_toks
            with self._state_lock:
                params = self.params  # consistent with any live swap
            cache, _last = prefill(
                params, jnp.asarray(arr),
                jnp.asarray([len(prefix_toks)], np.int32), self.cfg,
                total_len=w)
            with self._state_lock:
                self._state["pool"] = store_blocks(
                    self._state["pool"], jnp.asarray(blocks, np.int32),
                    cache)
            return self._export_ids(blocks)
        finally:
            with self._prefix_lock:
                for b in blocks:
                    self._alloc.free(b)

    def export_prompt(self, tokens: list[int],
                      timeout: float | None = None) -> dict:
        """Prefill-role handoff: compute the prompt's KV on THIS replica
        and export the blocks backing its leading positions as a payload
        a decode replica can :meth:`import_prompt` — the prefill half of
        disaggregated serving.

        The exported prefix is the prompt minus its last token: the
        importer re-prefills that one token through the imported blocks
        (exactly the suffix math a colocated prefix-cache hit runs), so
        its admission recovers the true last-position logits and greedy
        output stays pinned against a colocated replica. Int8 pools
        export codes AND scales verbatim — a quantized handoff is never
        re-quantized, so it is exact by construction.

        With the prefix cache on, the prefix rides the NORMAL pure-
        prefill admission (``want=0`` through the scheduler: suffix
        reuse against this replica's trie — prefix-affine routing
        concentrates shared prefixes here — queue-wait accounting,
        publish-on-finish), and the published entry's blocks are the
        export source. Without it, the prefix is prefilled into scratch
        blocks and freed after the export."""
        if self._alloc is None:
            raise ValueError("prompt handoff requires kv_layout='paged'")
        toks = [int(t) for t in tokens][: self.prefill_len]
        if len(toks) < 2:
            raise ValueError("prompt handoff needs a >=2-token prompt")
        plen = len(toks) - 1
        key = tuple(toks[:plen])
        cache = self.prefix_cache
        entry = None
        if cache is not None and plen >= cache.min_len:
            with self._prefix_lock:
                known = cache.has(key)
            if not known:
                # Pure prefill through the scheduler; publish-on-finish
                # pools the prompt's blocks for the export below (and
                # for the next same-prefix export).
                self.submit(list(key), 0).result(timeout)
            with self._prefix_lock:
                m = cache.match(toks)  # pins the entry against eviction
                if m is not None:
                    entry, depth = m
                    # Cap at the positions the entry's blocks actually
                    # back (publish can cap), and keep min_len useful.
                    depth = min(depth, len(entry.blocks or ())
                                * self.kv_block_size)
                    if depth >= cache.min_len:
                        plen = depth
                    else:
                        cache.release(entry)
                        entry = None
        try:
            if entry is not None:
                ids = list(entry.blocks[: self._alloc.blocks_for(plen)])
                payload = self._export_ids(ids)
            else:
                payload = self._export_cold(toks[:plen])
        finally:
            if entry is not None:
                with self._prefix_lock:
                    cache.release(entry)
        with self._mlock:
            self.kv_handoff_exports += 1
            self.kv_handoff_tokens += plen
        # tp_shards records the exporter's mesh shape. The payload is
        # already host-global (the sharded pool gathers on device_get),
        # so a differently-sharded importer scatters it with ITS pool
        # sharding — the reshard is the import itself.
        return {"tokens": toks, "prefix_len": plen,
                "block_size": self.kv_block_size,
                "kv_dtype": self.kv_dtype, "tp_shards": self.tp_shards,
                "cp_shards": self.cp_shards, "pp_stages": self.pp_stages,
                "payload": payload}

    def import_prompt(self, handoff: dict) -> bool:
        """Decode-role handoff receive: allocate local blocks, scatter
        the exported payload in VERBATIM (int8 codes + scales included),
        and register the prefix in this replica's trie — the subsequent
        ``submit()`` of the full prompt rides the ordinary prefix-hit
        admission (full blocks refcount-shared, at most one tail CoW),
        which is pinned byte-identical to a colocated decode.

        Returns False when the import cannot be registered (no prefix
        cache, prefix under ``min_len``, every cache slot pinned, or no
        free blocks) — the caller falls back to a plain submit and this
        replica prefills the prompt itself: degraded, never wrong.
        Raises ``ValueError`` on a payload whose block size, kv dtype,
        or block count does not match this pool (importing it would
        corrupt KV)."""
        if self._alloc is None:
            raise ValueError("prompt handoff requires kv_layout='paged'")
        if int(handoff["block_size"]) != self.kv_block_size:
            raise ValueError(
                f"handoff block_size {handoff['block_size']} != "
                f"pool block_size {self.kv_block_size}")
        if str(handoff.get("kv_dtype", "fp")) != self.kv_dtype:
            raise ValueError(
                f"handoff kv_dtype {handoff.get('kv_dtype')!r} != "
                f"pool kv_dtype {self.kv_dtype!r}")
        toks = [int(t) for t in handoff["tokens"]]
        plen = int(handoff["prefix_len"])
        if not 0 < plen <= min(len(toks), self.prefill_len):
            raise ValueError(f"bad handoff prefix_len {plen}")
        payload = handoff["payload"]
        cache = self.prefix_cache
        if cache is None or plen < cache.min_len:
            return False
        nblk = self._alloc.blocks_for(plen)
        if self._payload_nblk(payload) != nblk:
            raise ValueError(
                f"handoff payload carries {self._payload_nblk(payload)} "
                f"blocks; prefix_len {plen} needs {nblk}")
        imported = self._install_prefix_payload(tuple(toks[:plen]),
                                                payload)
        if imported:
            with self._mlock:
                self.kv_handoff_imports += 1
                self.kv_handoff_tokens += plen
        return imported

    def _install_prefix_payload(self, key: tuple, payload: dict, *,
                                version: int | None = None) -> bool:
        """Allocate local blocks, scatter ``payload`` in VERBATIM, and
        register ``key`` in the trie — the re-import core shared by the
        peer handoff (:meth:`import_prompt`) and host-tier promotion
        (:meth:`_promote_host_prefix`). Returns False when it cannot
        land (no free blocks, every trie slot pinned). ``version``
        stamps the installed entry's weights epoch (None = the live
        one: peer handoffs in a weight-streaming fleet are assumed
        version-aligned — the broadcast's ``max_lag`` bounds the skew)."""
        cache = self.prefix_cache
        nblk = self._alloc.blocks_for(len(key))
        if self._payload_nblk(payload) != nblk:
            raise ValueError(
                f"payload carries {self._payload_nblk(payload)} blocks; "
                f"prefix_len {len(key)} needs {nblk}")
        with self._prefix_lock:
            if cache.has(key):
                cache.touch(key)
                return True
            self._reclaim_blocks(nblk)
            if not self._alloc.can_alloc(nblk):
                return False
            blocks = self._alloc.alloc(nblk)
            self.kv_blocks_peak = max(self.kv_blocks_peak,
                                      self._alloc.blocks_in_use)
        # Device scatter OUTSIDE the prefix lock: the dispatch must
        # wait out any in-flight decode chunk (state lock), and holding
        # the prefix lock across that wait would stall the scheduler's
        # pop path — every import would freeze admissions for a chunk.
        # The blocks are ours alone until registered, so nothing reads
        # them early.
        try:
            # Same power-of-two padding as the export (duplicate
            # scatter of identical data is deterministic), so the
            # import executables stay bounded too.
            pad = pow2_bucket(nblk) - nblk
            ids = blocks + [blocks[-1]] * pad

            def _pad(node):
                if isinstance(node, dict):
                    return {k: _pad(v) for k, v in node.items()}
                if pad == 0:
                    return jnp.asarray(node)
                return jnp.asarray(np.concatenate(
                    [node] + [node[:, -1:]] * pad, axis=1))

            with self._state_lock:
                self._state["pool"] = import_blocks(
                    self._state["pool"], jnp.asarray(ids, np.int32),
                    {s: _pad(payload[s]) for s in ("k", "v")})
        except Exception:
            with self._prefix_lock:
                for b in blocks:
                    self._alloc.free(b)
            raise
        with self._prefix_lock:
            entry = cache.reserve(key)
            if entry is None:
                # A peer import won the reserve race (its blocks carry
                # identical content — the key IS the data), or every
                # cache slot is pinned. Either way our blocks are
                # surplus.
                for b in blocks:
                    self._alloc.free(b)
                imported = cache.has(key)
            else:
                entry.blocks = tuple(blocks)
                # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; swap flush re-sweeps
                entry.version = (self.weights_version
                                 if version is None else int(version))
                with self._mlock:
                    self.prefix_inserts += 1
                imported = True
        if imported:
            # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; hints validate on pull
            ver = self.weights_version if version is None else int(version)
            self._publish_directory(key, len(key), ver, tier="hbm")
        return imported

    def _promote_host_prefix(self, tokens: list[int],
                             timeline=None, *,
                             allow_stale: bool = False) -> bool:
        """Second-chance lookup: a trie miss probes the host tier for
        the longest demoted prefix of ``tokens`` and re-imports it
        through :meth:`_install_prefix_payload` — the admission then
        rides the ordinary prefix-hit path instead of a cold prefill.
        The payload stays in the tier (unpinned LRU): a later eviction
        of the promoted entry skips the re-export. ``allow_stale``
        (resumed suspended streams only) accepts payloads from an
        older weights epoch; fresh requests only match the live one."""
        with self._prefix_lock:
            # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; stale entry refused
            live_epoch = self.weights_version
            m = self._host_tier.match(
                tokens, None if allow_stale else live_epoch)
        if m is None:
            return False
        entry, depth = m
        if (self.prefix_cache is None
                or depth < self.prefix_cache.min_len):
            return False
        nblk = self._alloc.blocks_for(depth)

        def _slice(node):
            if isinstance(node, dict):
                return {k: _slice(v) for k, v in node.items()}
            return node[:, :nblk]

        # Causality: the payload's leading blocks back ANY depth <= its
        # own, so an interior match imports just the covering slice.
        payload = {s: _slice(entry.payload[s]) for s in ("k", "v")}
        if not self._install_prefix_payload(tuple(entry.key[:depth]),
                                            payload,
                                            version=entry.version):
            return False
        with self._prefix_lock:
            self._host_tier.note_promotion()
        with self._mlock:
            self.kv_host_hits += 1
        if timeline is not None:
            timeline.event("promote", prefix_len=depth)
        return True

    # -- fleet KV economy (HBM -> host -> peer -> cold) ----------------

    @staticmethod
    def _slice_payload(payload: dict, nblk: int) -> dict:
        """Covering slice of a handoff payload's leading ``nblk``
        blocks (causality: the leading blocks back any shorter depth,
        fp arrays and int8 {"q","scale"} dicts alike)."""

        def _s(node):
            if isinstance(node, dict):
                return {k: _s(v) for k, v in node.items()}
            return node[:, :nblk]

        return {side: _s(payload[side]) for side in ("k", "v")}

    def _publish_directory(self, key_tokens, prefix_len: int,
                           version: int, *, tier: str) -> None:
        """Advertise a held prefix to the fleet directory (keyed by the
        same affinity hash the gateway routes on). Cheap enough for the
        hot publish/demote paths: one leaf-locked dict write, no fleet
        round-trip — the directory stores hints and the pull validates."""
        if self.kv_directory is None:
            return
        holder = COLD_HOLDER if tier == "cold" else self.replica_name
        if not holder:
            return  # anonymous replica: nothing a peer could pull from
        key = prefix_affinity_key(key_tokens, self.kv_affinity_tokens)
        self.kv_directory.publish(key, holder,
                                  prefix_len=int(prefix_len),
                                  version=int(version), tier=tier)
        with self._mlock:
            self.kv_directory_publishes += 1

    def _demote_to_cold(self, entry) -> None:
        """Host-tier eviction hook (HostKvTier.on_evict, fired under
        the prefix lock): pack the dying payload into the shared
        content-addressed cold store and publish the hint BEFORE the
        bytes drop — the long tail demotes instead of vanishing. The
        epoch rides the content key, so a pre-swap payload parked here
        is unreachable to post-swap lookups by construction."""
        # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; stale payloads just drop
        if entry.version != self.weights_version:
            return  # stale epoch: parking it would waste cold bytes
        if self.cold_store is None or entry.prefix_len < 1:
            return
        handoff = {"tokens": list(entry.key[: entry.prefix_len]),
                   "prefix_len": int(entry.prefix_len),
                   "block_size": self.kv_block_size,
                   "kv_dtype": self.kv_dtype,
                   "tp_shards": self.tp_shards,
                   "cp_shards": self.cp_shards,
                   "pp_stages": self.pp_stages,
                   "payload": entry.payload}
        if self.cold_store.put(handoff, version=entry.version) is None:
            return
        with self._mlock:
            self.kv_cold_demotions += 1
        self._publish_directory(entry.key, entry.prefix_len,
                                entry.version, tier="cold")

    def export_prefix(self, tokens: list[int]) -> dict:
        """Serve a peer's KV pull: export the deepest cached prefix of
        ``tokens`` this replica holds — trie (device blocks, one export
        round-trip) or host tier (already host-side, free) — as a PR-9
        handoff dict stamped with the live weights epoch
        (``weights_version`` key; the requester refuses the envelope if
        its own epoch has moved on, so a mid-pull weight push degrades
        to a refusal, never to garbage KV).

        Raises ``KeyError`` when nothing matches — the directory hint
        that sent the requester here was stale; it withdraws the hint
        and falls through to the cold store or a plain prefill."""
        if self._alloc is None:
            raise ValueError("prefix export requires kv_layout='paged'")
        toks = [int(t) for t in tokens]
        cache = self.prefix_cache
        entry, depth, host = None, 0, None
        with self._prefix_lock:
            # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; requester re-validates
            live = self.weights_version
            if cache is not None:
                m = cache.match(toks)  # pins against eviction
                if m is not None:
                    entry, depth = m
                    depth = min(depth, len(entry.blocks or ())
                                * self.kv_block_size)
                    if depth < cache.min_len or \
                            getattr(entry, "version", 0) != live:
                        cache.release(entry)
                        entry, depth = None, 0
            if self._host_tier is not None:
                hm = self._host_tier.match(toks, live)
                if hm is not None and hm[1] > depth:
                    host = hm
        try:
            if host is not None:
                hentry, plen = host
                payload = self._slice_payload(
                    hentry.payload, self._alloc.blocks_for(plen))
            elif entry is not None:
                plen = depth
                ids = list(entry.blocks[: self._alloc.blocks_for(plen)])
                payload = self._export_ids(ids)
            else:
                raise KeyError("no cached prefix to export")
        finally:
            if entry is not None:
                with self._prefix_lock:
                    cache.release(entry)
        with self._mlock:
            self.kv_handoff_exports += 1
            self.kv_handoff_tokens += plen
        return {"tokens": toks[:plen], "prefix_len": plen,
                "block_size": self.kv_block_size,
                "kv_dtype": self.kv_dtype, "tp_shards": self.tp_shards,
                "cp_shards": self.cp_shards, "pp_stages": self.pp_stages,
                "weights_version": live, "payload": payload}

    def _local_prefix_depth(self, toks: list[int]) -> tuple[int, int]:
        """(best local tier depth, live epoch) for the crossover check:
        the deepest of trie and host-tier match at the live weights
        epoch — anything a remote import must BEAT to be worth its
        fixed pull cost."""
        cache = self.prefix_cache
        with self._prefix_lock:
            # tpu-lint: disable=lock-inconsistent-guard -- epoch fence; install re-validates
            live = self.weights_version
            local = 0
            m = cache.match(toks)
            if m is not None:
                ent, d = m
                cache.release(ent)
                if getattr(ent, "version", 0) == live:
                    local = min(d, len(ent.blocks or ())
                                * self.kv_block_size)
            if self._host_tier is not None:
                hm = self._host_tier.match(toks, live)
                if hm is not None:
                    local = max(local, hm[1])
        return local, live

    def _maybe_import_remote(self, tokens: list[int],
                             timeline=None) -> bool:
        """The fleet miss path: trie -> host -> PEER -> COLD ->
        prefill. Runs on the CALLER thread in :meth:`submit` with no
        decoder lock held across a fetch (the pop loop plans prefixes
        under the scheduler condition — blocking I/O there would stall
        every submit; the tpu-lint lock-blocking-call fixture pair pins
        the shape). A successful import installs through
        :meth:`_install_prefix_payload`, so the pop-time plan sees an
        ordinary trie hit and prefills only the tail."""
        cache = self.prefix_cache
        if cache is None or self._alloc is None:
            return False
        if self.kv_directory is None and self.cold_store is None:
            return False
        toks = [int(t) for t in tokens]
        cap = min(len(toks) - 1, self.prefill_len)
        if cap < cache.min_len:
            return False
        local, live = self._local_prefix_depth(toks)
        # Recompute-vs-import crossover: the pull's fixed cost (RTT +
        # envelope codec + scatter dispatch) only amortizes when the
        # import saves at least this many prefill tokens over the best
        # local tier.
        want = max(cache.min_len,
                   local + max(1, self.kv_import_crossover_tokens))
        if want > cap:
            return False
        key = prefix_affinity_key(toks, self.kv_affinity_tokens)
        best_remote = 0
        if self._import_from_peers(key, toks, cap, want, live,
                                   timeline):
            return True
        if self.kv_directory is not None:
            for hint in self.kv_directory.lookup(key, version=live):
                best_remote = max(best_remote, hint.prefix_len)
        if self._import_from_cold(toks, cap, want, live, timeline):
            return True
        if self.cold_store is not None:
            best_remote = max(best_remote,
                              self.cold_store.peek_depth(toks, live))
        with self._mlock:
            if local < best_remote < want:
                self.kv_import_skipped_crossover += 1
            else:
                self.kv_peer_misses += 1
        return False

    def _import_from_peers(self, key: str, toks: list[int], cap: int,
                           want: int, live: int, timeline) -> bool:
        """Probe directory holders deepest-first; the fetch validates
        everything the hint merely promised. A dead or evicted holder
        costs one withdrawn hint, never a hang — the next holder, the
        cold store, and plain prefill are all still behind it."""
        if self.kv_directory is None or self._peer_fetch is None:
            return False
        hints = [h for h in self.kv_directory.lookup(
                     key, exclude=(self.replica_name, COLD_HOLDER),
                     version=live)
                 if h.prefix_len >= want]
        for hint in hints:
            try:
                got = self._peer_fetch(hint.holder, toks, live)
            except Exception:
                got = None
            if got is None:
                with self._mlock:
                    self.kv_peer_fetch_failures += 1
                self.kv_directory.withdraw(key, hint.holder)
                continue
            try:
                from kubeflow_tpu.serving import handoff as handoff_mod

                h = handoff_mod.unpack(got["envelope"])
                ver = int(got.get("weights_version", live))
            except (ValueError, KeyError, TypeError):
                with self._mlock:
                    self.kv_peer_fetch_failures += 1
                self.kv_directory.withdraw(key, hint.holder)
                continue
            if self._install_remote(h, ver, toks, cap, want,
                                    timeline, tier="peer"):
                return True
        return False

    def _import_from_cold(self, toks: list[int], cap: int, want: int,
                          live: int, timeline) -> bool:
        if self.cold_store is None:
            return False
        got = self.cold_store.match(toks, live)
        if got is None:
            return False
        h, depth = got
        return self._install_remote(h, live, toks, min(cap, depth),
                                    want, timeline, tier="cold")

    def _install_remote(self, h: dict, ver: int, toks: list[int],
                        cap: int, want: int, timeline,
                        tier: str) -> bool:
        """Validate a fetched envelope against THIS pool and the LIVE
        weights epoch, then install its covering slice. The epoch
        re-read is the mid-pull staleness gate: a weight push that
        landed while the envelope was in flight makes ``ver`` stale
        and the envelope is refused — counted, never installed."""
        if int(h["block_size"]) != self.kv_block_size or \
                str(h.get("kv_dtype", "fp")) != self.kv_dtype:
            with self._mlock:
                self.kv_peer_fetch_failures += 1
            return False
        with self._state_lock:
            now_live = self.weights_version
        if int(ver) != now_live:
            with self._mlock:
                self.kv_import_stale_refused += 1
            if timeline is not None:
                timeline.event("kv_import_refused", tier=tier,
                               stale_version=int(ver))
            return False
        # Actual matched depth (the hint and even the envelope's own
        # prefix_len may be optimistic — a different prompt family can
        # share an affinity key).
        ht = h["tokens"]
        lim = min(int(h["prefix_len"]), cap, len(ht))
        d = 0
        while d < lim and int(ht[d]) == toks[d]:
            d += 1
        if d < want:
            return False
        payload = self._slice_payload(h["payload"],
                                      self._alloc.blocks_for(d))
        if not self._install_prefix_payload(tuple(toks[:d]), payload,
                                            version=now_live):
            return False
        nbytes = payload_nbytes(payload)
        with self._mlock:
            if tier == "cold":
                self.kv_cold_hits += 1
                self.kv_cold_import_bytes += nbytes
            else:
                self.kv_peer_hits += 1
                self.kv_peer_import_bytes += nbytes
        if timeline is not None:
            timeline.event("kv_import", tier=tier, prefix_len=d)
        return True

    # -- live weight streaming -----------------------------------------

    def update_weights(self, params, *, version: int | None = None,
                       draft_params=None) -> int:
        """Zero-drain in-place weight swap: install a new param pytree
        between dispatches without dropping a single live stream.

        Double-buffered by construction: the new tree is placed onto
        the EXISTING shardings (tp>1 reuses shard_pytree + the model's
        partition rules, so a host-gathered push from any learner mesh
        lands correctly — the placement IS the reshard, the same trick
        as the handoff envelope) with NO lock held, while decode keeps
        dispatching against the old buffers; the install itself is a
        pointer swap under the state lock — the dispatch boundary — so
        no decode step can ever see torn weights. Live streams keep
        their slots and KV and continue across the boundary (their
        token sequences are consistent with exactly one version
        switch, never an interleave); prompt K/V cached under the old
        weights is flushed/refused so post-swap admissions are
        byte-identical to a decoder cold-started on the new weights.

        ``version`` stamps the push (monotonic; a stale or duplicate
        version is a no-op returning the installed epoch — stragglers
        in a fleet broadcast converge on the next push); None
        auto-increments. ``draft_params`` swaps a paired
        DraftModelProposer's weights in the SAME state-lock epoch —
        target and draft can never serve different versions, which
        would silently collapse speculative acceptance.

        Returns the installed weights epoch."""
        t0 = time.perf_counter()
        # One consistent (params, epoch) snapshot to validate against.
        with self._state_lock:
            cur_params, cur_version = self.params, self.weights_version
        if version is not None and int(version) <= cur_version:
            return cur_version
        # Shape/dtype contract against the serving tree (tree.map
        # raises on a structure mismatch); dtype casts on host so a
        # f32 learner can push into a bf16 server.
        def _fit(n, o):
            if tuple(getattr(n, "shape", ())) != tuple(o.shape):
                raise ValueError(
                    f"pushed leaf shape {getattr(n, 'shape', None)} "
                    f"!= serving shape {o.shape}")
            n = np.asarray(n) if not hasattr(n, "dtype") else n
            return n.astype(o.dtype) if n.dtype != o.dtype else n

        params = jax.tree.map(_fit, params, cur_params)
        # Double buffer: place outside every lock. The old buffers
        # keep serving dispatches while the host→device copy streams.
        if self.mesh is not None:
            from kubeflow_tpu.models.transformer import partition_rules
            from kubeflow_tpu.parallel.sharding import shard_pytree

            new_params = shard_pytree(params, self.mesh,
                                      partition_rules(self.cfg))
        else:
            new_params = jax.device_put(params)
        jax.block_until_ready(new_params)
        spec = self._spec
        draft_new = None
        if draft_params is not None:
            if spec is None or not hasattr(spec, "params"):
                raise ValueError(
                    "draft_params given but no draft-model proposer is "
                    "configured (draft_mode='model:<name>')")
            draft_new = jax.device_put(
                jax.tree.map(_fit, draft_params, spec.params))
            jax.block_until_ready(draft_new)
        t_swap = time.perf_counter()
        with self._state_lock:
            # Re-check under the lock: a concurrent higher-versioned
            # push may have won while our buffers streamed in.
            if version is not None and int(version) <= \
                    self.weights_version:
                return self.weights_version
            self.params = new_params
            if draft_new is not None:
                spec.install_weights(draft_new)
            self.weights_version = (int(version) if version is not None
                                    else self.weights_version + 1)
            new_version = self.weights_version
        swap_s = time.perf_counter() - t_swap
        trie_flushed, tier_flushed = self._flush_stale_kv(new_version)
        total_s = time.perf_counter() - t0
        self._g_weights_version.set(new_version)
        self._c_weight_pushes.inc()
        self._h_weight_push.observe(total_s)
        with self._mlock:
            self.weight_pushes += 1
            # The stall decode actually pays: waiting out the in-flight
            # dispatch for the lock plus the pointer swap — the number
            # the bench gates at <= one dispatch gap.
            self.last_swap_seconds = swap_s
        tl = self.trace.start(f"weights-v{new_version}")
        tl.event("push", version=new_version,
                 place_ms=round(1e3 * (t_swap - t0), 3),
                 draft=draft_new is not None)
        tl.event("swap", swap_ms=round(1e3 * swap_s, 3))
        if trie_flushed or tier_flushed:
            tl.event("flush", trie_entries=trie_flushed,
                     tier_entries=tier_flushed)
        tl.close()
        return new_version

    def _flush_stale_kv(self, version: int) -> tuple[int, int]:
        """Drop cached K/V computed under a pre-swap weights epoch:
        unpinned stale trie entries are removed outright (their blocks
        free; demotion is skipped — see :meth:`_demote_entry`) and
        unpinned stale host-tier payloads discarded. Entries pinned by
        in-flight admissions survive the sweep but are refused and
        removed at their next match (:meth:`_plan_prefix`); PINNED
        tier payloads are suspended streams' state and straddle the
        swap by design."""
        trie_flushed = tier_flushed = 0
        with self._prefix_lock:
            if self.prefix_cache is not None:
                for entry in self.prefix_cache.entries():
                    if entry.version != version and entry.refs == 0:
                        self.prefix_cache.remove(entry)
                        trie_flushed += 1
            if self._host_tier is not None:
                for e in self._host_tier.entries():
                    if e.version != version and not e.pinned:
                        self._host_tier.discard(e.key)
                        tier_flushed += 1
        return trie_flushed, tier_flushed

    # -- QoS: ordering, deadline shedding, stream suspension -----------

    def _order_pending_locked(self, now: float) -> None:
        """Re-order the pending deque by QoS policy (called under the
        cv): weighted fair share across tenants (tokens served over
        weight, lowest first), then priority with starvation aging,
        then FIFO — the scheduler queue's ordering applied to
        inference admission. The sort is stable, so equal keys keep
        their arrival order."""
        qos = self.qos
        with self._mlock:
            served = dict(self._tenant_served)
        self._pending = deque(sorted(
            self._pending,
            key=lambda r: order_key(
                served=served.get(r.tenant, 0.0),
                weight=qos.spec(r.tenant).weight,
                priority=r.priority,
                waited_seconds=now - r.submit_t,
                aging_seconds=qos.aging_seconds,
                submit_t=r.submit_t)))

    def _shed_expired_locked(self, now: float) -> None:
        """Shed queued requests whose deadline already passed (under
        the cv): decode compute spent on an answer nobody is waiting
        for only starves the requests that still have time."""
        expired = [r for r in self._pending
                   if r.deadline_t is not None and now > r.deadline_t]
        if not expired:
            return
        dead = {id(r) for r in expired}
        self._pending = deque(r for r in self._pending
                              if id(r) not in dead)
        with self._mlock:
            self.qos_deadline_shed += len(expired)
        for r in expired:
            if r.timeline is not None:
                r.timeline.event("deadline_shed",
                                 waited_ms=round(1e3 * (now - r.submit_t),
                                                 3))
            self._finish(r, error=DeadlineExceeded(
                f"deadline passed after {now - r.submit_t:.3f}s in queue"))

    def _pick_suspend_victim_locked(self, cand: _Request,
                                    need: int) -> int:
        """Choose a live stream to SUSPEND so the memory-blocked
        ``cand`` can admit: the lowest-base-priority stream STRICTLY
        below the candidate's base priority, whose exported KV fits
        the host tier and whose blocks actually clear the candidate's
        watermark. Base priorities on both sides deliberately: aging
        orders the QUEUE (a starved request eventually pops first) but
        must never drive preemption — an aged equal-priority candidate
        suspending a peer would ping-pong streams of one tenant
        through the host tier forever. Called with the cv AND prefix
        lock held (it reads allocator and tier state). Returns -1 when
        nothing qualifies — the round then defers exactly as before
        QoS existed."""
        if self.qos is None or self._host_tier is None:
            return -1
        victim, victim_p = -1, None
        for slot in range(self.slots):
            r = self._slot_req[slot]
            if r is None or r.want_left <= 0:
                continue
            if r.chunk_pos >= 0:
                # Mid-chain chunked admission: its row holds a partial
                # prompt that never decoded a token — there is no
                # sequence-so-far to export, only work to throw away.
                continue
            if len(r.tokens) + len(r.out) - r.folded < 2:
                continue  # a 1-token sequence has no exportable prefix
            if r.priority >= cand.priority:
                continue
            if victim_p is None or r.priority < victim_p:
                victim, victim_p = slot, r.priority
        if victim < 0:
            return -1
        r = self._slot_req[victim]
        plen = len(r.tokens) + len(r.out) - r.folded - 1
        est = (self._alloc.blocks_for(plen) * self.kv_block_size
               * self._host_bytes_per_token)
        if not self._host_tier.can_fit(est):
            return -1  # suspension must never strand an unresumable stream
        freed = len(self._slot_blocks[victim])
        if self._alloc.free_blocks + freed - need < self.kv_low_watermark:
            return -1  # even suspending wouldn't admit the candidate
        return victim

    def _suspend_stream(self, slot: int) -> None:
        """Park the live stream in ``slot``: retire its device row,
        export the KV backing its sequence-so-far into the host tier
        (PINNED — resume byte-identity depends on those exact bytes),
        free the slot and its blocks, and requeue the request. Resume
        is the ordinary pop-loop admission: the parked request's
        tokens now include everything it emitted, so it prefix-hits
        the promoted payload and continues exactly where it stopped —
        inference preemption as data-exact as the training
        scheduler's. Runs on the scheduler thread with no locks held.
        """
        req = self._slot_req[slot]
        if req is None:
            return
        seq = req.tokens + req.out[req.folded:]
        plen = len(seq) - 1
        ids = self._slot_blocks[slot][: self._alloc.blocks_for(plen)]
        # Retire the row FIRST: its blocks return to the pool below,
        # and a still-active row would scatter the next step's K/V
        # through freed (possibly re-allocated) blocks — the PR-8
        # stale-row hazard, parked the same way device-side EOS is.
        with self._state_lock:
            self._state = retire_row(self._state, slot)
        payload = self._export_ids(ids)
        key = tuple(seq[:plen])
        with self._prefix_lock:
            parked = self._host_tier.put(key, payload, plen, pinned=True,
                                         version=req.weights_version)
        self._slot_req[slot] = None
        self._active_count -= 1
        self._release_pin(req)
        self._free_slot_blocks(slot)
        if parked:
            req.host_key = key
        elif len(seq) > self.prefill_len:
            # No host copy AND too long to re-prefill cold: the stream
            # cannot resume. Unreachable while the victim pick checks
            # can_fit, but never park an unresumable request.
            self._finish(req, error=MemoryError(
                "suspended stream lost its KV payload"))
            return
        req.tokens = seq
        req.folded = len(req.out)
        req.admit_plan = None
        req.submit_t = time.perf_counter()  # queue wait re-anchors at park
        if req.timeline is not None:
            req.timeline.event("suspend", emitted=len(req.out),
                               prefix_len=plen)
        with self._mlock:
            self.kv_suspends += 1
        with self._cv:
            if self._stopped:
                self._finish(req, error=RuntimeError("decoder stopped"))
                return
            self._pending.append(req)
            self._cv.notify()

    def _mark_admitted(self, req: _Request, slot: int) -> None:
        """Record the pop→slot transition: queue-wait histogram + the
        timeline's admitted event (deferral rounds stretch this wait —
        exactly the signal the admission instrumentation must carry).
        A resumed (previously suspended) request re-anchors its wait at
        park time, so the histograms measure the park, not the whole
        stream lifetime."""
        wait = time.perf_counter() - req.submit_t
        self._h_queue_wait.observe(wait)
        self._h_tenant_wait.labels(tenant_bucket(req.tenant)).observe(wait)
        if req.out:
            # Tokens already emitted == this is a suspended stream
            # coming back; once admitted, its pinned host-tier payload
            # becomes ordinary second-chance cache.
            if req.host_key is not None and self._host_tier is not None:
                with self._prefix_lock:
                    self._host_tier.unpin(req.host_key)
                req.host_key = None
            with self._mlock:
                self.kv_resumes += 1
            if req.timeline is not None:
                req.timeline.event("resume", emitted=len(req.out),
                                   want_left=req.want_left)
        if req.timeline is not None:
            req.timeline.event("admitted", slot=slot,
                               wait_ms=round(1e3 * wait, 3))

    def _post_admit(self, req: _Request, slot: int) -> None:
        if req.want_left == 0:
            # Pure prefill (caller wants last-position logits only): the row
            # was inserted inactive; publish its prefix, then hand the
            # result back immediately.
            self._publish_prefix(req, slot)
            self._release_pin(req)
            self._free_slot_blocks(slot)
            self._slot_req[slot] = None
            self._finish(req)
        else:
            self._slot_req[slot] = req
            self._active_count += 1
            self.peak_in_flight = max(self.peak_in_flight,
                                      self._active_count)
            if self._spec is not None:
                self._spec.reset(slot)
                self._slot_k[slot] = self.speculative_k

    def _dispatch(self, toks: np.ndarray, emitted: np.ndarray) -> None:
        """Route one step's sampled tokens ([slots]) to their requests.
        EOS parking already happened on device (``_decode_step_body``);
        the host only finishes the request and frees the slot."""
        now = time.perf_counter()
        emitted_n, ttft_sum, ttft_n = 0, 0.0, 0
        tenant_tok: dict[str, int] = {}
        for slot in range(self.slots):
            req = self._slot_req[slot]
            if req is None or not emitted[slot]:
                continue
            tok = int(toks[slot])
            req.out.append(tok)
            tenant_tok[req.tenant] = tenant_tok.get(req.tenant, 0) + 1
            if req.ttft_s is None:
                req.ttft_s = now - req.submit_t
                ttft_sum += req.ttft_s
                ttft_n += 1
                self._h_ttft.observe(req.ttft_s)
                if req.timeline is not None:
                    req.timeline.event("first_token")
            else:
                if req.last_emit_t is not None:
                    self._h_itl.observe(now - req.last_emit_t)
                if req.timeline is not None:
                    req.timeline.event("dispatch", tokens=1)
            req.last_emit_t = now
            req.stream.put(tok)
            emitted_n += 1
            hit_eos = self.eos_id is not None and tok == self.eos_id
            if hit_eos or len(req.out) >= req.want:
                # Publish the finished prompt's prefix while its K/V rows
                # are still intact in the slot, then free it.
                self._publish_prefix(req, slot)
                self._release_pin(req)
                self._free_slot_blocks(slot)
                self._slot_req[slot] = None
                self._active_count -= 1
                self._finish(req, reason="eos" if hit_eos else "length")
        with self._mlock:
            self.tokens_emitted += emitted_n
            self.ttft_sum += ttft_sum
            self.ttft_count += ttft_n
            for t, n in tenant_tok.items():
                self._tenant_served[t] = self._tenant_served.get(t, 0.0) + n

    def _dispatch_block(self, toks: np.ndarray, emitted: np.ndarray) -> None:
        """Route one verify step's tokens ([slots, K+1], ``emitted`` a
        per-row prefix mask) to their requests — the multi-token sibling
        of :func:`_dispatch`. The device already capped each row at its
        budget and truncated at EOS, so the mask is trusted verbatim."""
        now = time.perf_counter()
        emitted_n, ttft_sum, ttft_n = 0, 0.0, 0
        tenant_tok: dict[str, int] = {}
        for slot in range(self.slots):
            req = self._slot_req[slot]
            if req is None or not emitted[slot, 0]:
                continue
            last_tok = None
            row_emitted = 0
            first_here = req.ttft_s is None
            for j in range(toks.shape[1]):
                if not emitted[slot, j]:
                    break
                last_tok = int(toks[slot, j])
                req.out.append(last_tok)
                if req.ttft_s is None:
                    req.ttft_s = now - req.submit_t
                    ttft_sum += req.ttft_s
                    ttft_n += 1
                    self._h_ttft.observe(req.ttft_s)
                    if req.timeline is not None:
                        req.timeline.event("first_token")
                req.stream.put(last_tok)
                emitted_n += 1
                row_emitted += 1
            if row_emitted:
                tenant_tok[req.tenant] = (tenant_tok.get(req.tenant, 0)
                                          + row_emitted)
                if req.last_emit_t is not None:
                    self._h_itl.observe(now - req.last_emit_t)
                req.last_emit_t = now
                if req.timeline is not None and not first_here:
                    req.timeline.event("dispatch", tokens=row_emitted)
            hit_eos = self.eos_id is not None and last_tok == self.eos_id
            if hit_eos or len(req.out) >= req.want:
                self._publish_prefix(req, slot)
                self._release_pin(req)
                self._free_slot_blocks(slot)
                self._slot_req[slot] = None
                self._active_count -= 1
                self._finish(req, reason="eos" if hit_eos else "length")
        with self._mlock:
            self.tokens_emitted += emitted_n
            self.ttft_sum += ttft_sum
            self.ttft_count += ttft_n
            for t, n in tenant_tok.items():
                self._tenant_served[t] = self._tenant_served.get(t, 0.0) + n

    def _tune_slot(self, slot: int, accepted: int, drafted: int) -> None:
        """Shrink a slot's draft length while verification keeps throwing
        its drafts away (<50% kept — the verify pass is then mostly
        wasted compute), grow it back one step per clean sweep."""
        if drafted <= 0:
            return
        if accepted * 2 < drafted:
            self._slot_k[slot] = max(1, self._slot_k[slot] - 1)
        elif accepted == drafted:
            self._slot_k[slot] = min(self.speculative_k,
                                     self._slot_k[slot] + 1)

    def _spec_round(self) -> bool:
        """One speculative decode round: collect proposals for every live
        row, verify them all in ONE fused dispatch (``chunk_size`` verify
        steps when chunking), route the accepted tokens. Returns False —
        fall through to the plain decode path — when no row has a draft
        (a verify without drafts would pay two forwards for one token).
        """
        steps, k_w = self._verify_steps, self.speculative_k
        asks = []
        for slot in range(self.slots):
            req = self._slot_req[slot]
            if req is not None:
                # steps-1 extra chain tokens: each verify step's commit
                # consumes one, so the next step's slice starts after it.
                asks.append((slot, req.tokens + req.out,
                             steps * self._slot_k[slot] + steps - 1))
        props = self._spec.propose(asks)
        drafts = np.zeros((steps, self.slots, k_w), np.int32)
        dlens = np.zeros((steps, self.slots), np.int32)
        for slot, ctx, _n in asks:
            prop = props.get(slot) or []
            req = self._slot_req[slot]
            budget = req.want - len(req.out)  # tokens the row may still emit
            off = 0
            for s in range(steps):
                if budget <= 0:
                    break
                # A verify step emits dlen accepted drafts + 1 commit:
                # cap dlen so a near-done row doesn't drown its
                # acceptance stats (and the verify pass) in drafts the
                # budget could never emit.
                k_use = min(self._slot_k[slot], budget - 1)
                seg = prop[off: off + k_use]
                # Skip the token the commit pass emits between slices —
                # under full acceptance it IS the next chain token, so
                # without the skip every later slice arrives off-by-one.
                off += len(seg) + 1
                budget -= len(seg) + 1
                if not seg:
                    break
                drafts[s, slot, : len(seg)] = seg
                dlens[s, slot] = len(seg)
        if not dlens.any():
            return False
        self._h_occupancy.observe(self._active_count)
        t_disp = time.perf_counter()
        with self._state_lock:
            self._state, outs, emits = verify_chunk(
                self._state, self.params, self.cfg, jnp.asarray(drafts),
                jnp.asarray(dlens), self.top_k, self.eos_id,
                self.kv_fused, self._kmesh)
        with self._mlock:
            self.dispatches += 1
            self.spec_verify_dispatches += 1
            self.steps += 2 * steps  # scoring + commit forward per verify
        self._ramp_streak = 0
        outs, emits = jax.device_get((outs, emits))
        self._h_dispatch.labels("verify").observe(
            time.perf_counter() - t_disp)
        for s in range(steps):
            # Accounting before routing: routing may free the slot.
            drafted, accepted = 0, 0
            for slot in range(self.slots):
                d = int(dlens[s, slot])
                if d == 0 or self._slot_req[slot] is None:
                    continue
                m = int(emits[s, slot].sum())
                acc = min(max(m - 1, 0), d)
                drafted += d
                accepted += acc
                if m:
                    self._tune_slot(slot, acc, d)
            with self._mlock:
                self.spec_drafted_tokens += drafted
                self.spec_accepted_tokens += accepted
            self._dispatch_block(outs[s], emits[s])
        return True

    def _loop(self) -> None:
        """Scheduler-thread entry: run the loop, and on ANY exit — clean
        stop, inner-handler return, or an escaped exception — fail every
        stream still live so no StreamHandle ever hangs out its timeout
        waiting on a dead loop."""
        err: Exception = RuntimeError("decoder stopped")
        try:
            self._run()
        except Exception as e:
            err = e
        finally:
            self._fail_all(err)

    def _fail_all(self, err: Exception) -> None:
        with self._cv:
            self._stopped = True
            queued = list(self._pending)
            self._pending.clear()
        self._chunk_jobs.clear()
        for slot in range(self.slots):
            req = self._slot_req[slot]
            if req is not None:
                self._slot_req[slot] = None
                # Mid-chain chunked admissions occupy a slot without
                # counting as active (their row is parked, not decoding).
                if req.chunk_pos < 0:
                    self._active_count -= 1
                self._finish(req, error=err)
            # Every slot's block references return to the pool — also
            # covers blocks reserved at pop time for an admission that
            # never registered (idempotent with the finish path's free).
            self._free_slot_blocks(slot)
        for req in queued:
            self._finish(req, error=err)

    def _run(self) -> None:
        while True:
            idled = False
            with self._cv:
                while (not self._stopped and not self._pending
                       and self._active_count == 0
                       and not self._chunk_jobs):
                    idled = True
                    self._cv.wait(timeout=0.5)
                if self._stopped:
                    return
                now = time.perf_counter()
                self._shed_expired_locked(now)
                if self.qos is not None and len(self._pending) > 1:
                    self._order_pending_locked(now)
                pending = []
                deferred = False
                suspend_slot = -1
                free_slots = [s for s in range(self.slots)
                              if self._slot_req[s] is None]
                if self._alloc is None:
                    while free_slots and self._pending:
                        req = self._pending.popleft()
                        slot = free_slots.pop(0)
                        self._mark_admitted(req, slot)
                        pending.append((req, slot))
                else:
                    # Memory-aware admission: a request enters only when
                    # its WORST-CASE block count fits the pool (so the
                    # stream can never OOM mid-decode), reserving the
                    # blocks here so prime_prefix can't race them away.
                    # The prefix plan runs FIRST: a hit pins its entry
                    # (reclaim then can't evict it underneath) and
                    # shrinks the reservation to the non-shared blocks.
                    # The low-watermark defers admission while other
                    # work is in flight instead of draining the pool to
                    # zero headroom. Three QoS/fairness extensions ride
                    # on top: candidates arrive in fair-share/priority
                    # order; a memory-blocked head may be BYPASSED by
                    # up to hol_bypass_limit later candidates that fit
                    # (defer_rounds aging shields it from starving);
                    # and when the blocked candidate outranks a live
                    # stream, that stream is SUSPENDED to the host tier
                    # instead of the whole queue deferring.
                    idx = 0
                    bypassed = 0
                    while free_slots and idx < len(self._pending):
                        req = self._pending[idx]
                        worst = self._alloc.blocks_for(
                            max(len(req.tokens), 1) + req.want_left)
                        # TERMINAL size rejections (vs. the silent defer
                        # memory pressure takes): the request could
                        # never be served no matter how long it waits —
                        # either its worst-case block count exceeds the
                        # whole pool, or its tokens + budget overflow
                        # the virtual row. PromptTooLong -> HTTP 413.
                        if (worst > self._alloc.num_blocks
                                or len(req.tokens) + req.want_left
                                > self.total_len):
                            del self._pending[idx]
                            with self._mlock:
                                self.prompt_rejected_too_long += 1
                            self._finish(req, error=PromptTooLong(
                                f"request needs {worst} KV blocks "
                                f"({len(req.tokens)} prompt + "
                                f"{req.want_left} new tokens) but the "
                                f"pool holds {self._alloc.num_blocks} "
                                f"blocks / {self.total_len} tokens"))
                            continue
                        plan = (self._plan_prefix(req)
                                if self.prefix_cache is not None else None)
                        n_shared = (plan[1] // self.kv_block_size
                                    if plan is not None else 0)
                        need = worst - n_shared
                        fits = True
                        # A parked stream longer than the compiled
                        # prompt shape can only resume through its
                        # exported prefix — without a plan it waits for
                        # the promote to find memory, never cold-
                        # prefills a truncated sequence.
                        # (Chunked prefill lifts the cold ceiling: any
                        # in-row-bounds sequence can re-prefill as a
                        # chain of chunks, plan or no plan.)
                        resumable = (plan is not None
                                     or self.prefill_chunk_tokens > 0
                                     or len(req.tokens) <= self.prefill_len)
                        with self._prefix_lock:
                            self._reclaim_blocks(need, req.timeline)
                            headroom = self._alloc.free_blocks - need
                            busy = self._active_count > 0 or pending
                            if (not resumable
                                    or headroom < (self.kv_low_watermark
                                                   if busy else 0)):
                                fits = False
                                if plan is not None:
                                    self.prefix_cache.release(plan[0])
                                if not deferred:
                                    deferred = True
                                    suspend_slot = \
                                        self._pick_suspend_victim_locked(
                                            req, need)
                            else:
                                own = self._alloc.alloc(need)
                                shared = (list(plan[0].blocks[:n_shared])
                                          if plan is not None else [])
                                for b in shared:
                                    self._alloc.share(b)
                                self.kv_blocks_peak = max(
                                    self.kv_blocks_peak,
                                    self._alloc.blocks_in_use)
                        if fits:
                            req.admit_plan = plan
                            req.defer_rounds = 0
                            slot = free_slots.pop(0)
                            self._slot_blocks[slot] = shared + own
                            # The TABLE row stays sentinel until this
                            # request's own admission dispatch uploads
                            # it (_admit_prefix/_admit_batch). Pointing
                            # it at the blocks now would arm a
                            # stale-row write: an earlier admission's
                            # fused decode step in the SAME round still
                            # sees this slot's old device length, and
                            # its unconditional K/V scatter would land
                            # junk inside these blocks — including
                            # refcount-SHARED prefix blocks other
                            # streams read.
                            del self._pending[idx]
                            if bypassed:
                                with self._mlock:
                                    self.hol_bypasses += 1
                            self._mark_admitted(req, slot)
                            pending.append((req, slot))
                            continue
                        # Blocked: note the deferral, but keep scanning
                        # for a smaller candidate that fits — unless
                        # this head has aged past the bypass shield
                        # (then nothing younger may jump it again).
                        req.defer_rounds += 1
                        if req.timeline is not None:
                            req.timeline.event(
                                "deferred", need=need,
                                free=self._alloc.free_blocks)
                        if req.defer_rounds >= self.hol_shield_rounds:
                            break
                        bypassed += 1
                        if bypassed > self.hol_bypass_limit:
                            break
                        idx += 1
                if deferred:
                    with self._mlock:
                        self.kv_defer_admissions += 1
            if idled:
                # Coming out of idle: the streak cap must not outlive
                # the burst that set it — the next admission deserves
                # its ramp round. Reset OUTSIDE the cv so every
                # _ramp_streak access stays scheduler-thread-plain
                # (one site under the cv made the guard inconsistent).
                self._ramp_streak = 0
            try:
                if suspend_slot >= 0:
                    # Preempt-to-host: the victim was chosen under the
                    # cv, but the export is a device round-trip submits
                    # must not wait on — executed here, outside the cv.
                    # Its freed blocks admit the blocked candidate on
                    # the next round.
                    self._suspend_stream(suspend_slot)
                if pending:
                    # Admission fuses prefill + insert + one decode step
                    # into a single dispatch, so a new request's first
                    # token ships on the admission round-trip
                    # (prompt→token = 2 RTTs). Whether the round ALSO
                    # runs its chunk is the TTFT-ramp streak cap:
                    # normally an admission round ends here (fast first
                    # token, next round chunks), but under sustained
                    # arrivals (pending non-empty nearly every round) at
                    # most one consecutive admission-only round is
                    # allowed before a fused chunk runs in the same
                    # round — decode throughput must not degrade toward
                    # one dispatch per token. (want==0 admissions are
                    # pure prefills answered in _post_admit.)
                    #
                    # With the prefix cache on, each request first probes
                    # the trie: hits ride suffix-only admissions (one
                    # dispatch each), misses batch as before.
                    if self.prefill_chunk_tokens:
                        # Long admissions (suffix wider than one chunk)
                        # leave the one-dispatch paths: they register as
                        # chunk jobs and the pop loop feeds them one
                        # bounded chunk per round, interleaved with
                        # decode — a 32k admission no longer stalls
                        # every live stream for a monolithic prefill.
                        short = []
                        for req, slot in pending:
                            plan = req.admit_plan
                            plen = plan[1] if plan is not None else 0
                            if (len(req.tokens) - plen
                                    > self.prefill_chunk_tokens):
                                self._begin_chunked(req, slot)
                            else:
                                short.append((req, slot))
                        pending = short
                    misses = pending
                    if self.prefix_cache is not None:
                        hits, misses = [], []
                        for req, slot in pending:
                            # Paged admissions planned at pop time (the
                            # plan gates the block reservation); dense
                            # ones probe the trie here.
                            plan = (req.admit_plan
                                    if self._alloc is not None
                                    else self._plan_prefix(req))
                            if plan is None:
                                with self._mlock:
                                    self.prefix_misses += 1
                                misses.append((req, slot))
                            else:
                                hits.append((req, slot, plan))
                        for req, slot, (entry, plen, s) in hits:
                            self._admit_prefix(req, slot, entry, plen, s)
                    if misses:
                        self._admit_batch(misses)
                    ramp = (any(req.want_left for req, _ in pending)
                            and (self.chunk_size == 1
                                 or self._ramp_streak < 1))
                    if ramp:
                        self.ramp_rounds += 1
                        if self.chunk_size > 1:
                            self._ramp_streak += 1
                        # A ramp round still owes the oldest chunked
                        # admission its chunk — TTFT ramping must not
                        # starve a long prefill chain.
                        self._advance_chunked()
                        continue  # this round's step already ran
                self._advance_chunked()
                if self._active_count == 0:
                    continue
                if self._spec is not None and self._spec_round():
                    continue
                self._h_occupancy.observe(self._active_count)
                t_disp = time.perf_counter()
                if self.chunk_size > 1:
                    with self._state_lock:
                        self._state, toks, emitted = decode_chunk(
                            self._state, self.params, self.cfg,
                            self.chunk_size, self.top_k, self.eos_id,
                            self.kv_fused, self._kmesh,
                        )
                    with self._mlock:
                        self.steps += self.chunk_size
                        self.dispatches += 1
                    self._ramp_streak = 0
                    toks, emitted = jax.device_get((toks, emitted))
                    self._h_dispatch.labels("decode").observe(
                        time.perf_counter() - t_disp)
                    for k in range(self.chunk_size):
                        self._dispatch(toks[k], emitted[k])
                else:
                    with self._state_lock:
                        self._state, toks, emitted = decode_step(
                            self._state, self.params, self.cfg, self.top_k,
                            self.eos_id, self.kv_fused, self._kmesh,
                        )
                    with self._mlock:
                        self.steps += 1
                        self.dispatches += 1
                    toks, emitted = jax.device_get((toks, emitted))
                    self._h_dispatch.labels("decode").observe(
                        time.perf_counter() - t_disp)
                    self._dispatch(toks, emitted)
            except Exception as e:
                # A failed prefill/decode/verify may have invalidated
                # self._state (the jitted calls donate its buffers), so
                # the decoder cannot safely take more work. Requests
                # popped this round but not yet registered in a slot
                # would be invisible to the loop-exit sweep — fail them
                # here (returning any pop-time block reservation), then
                # let _loop's wrapper fail everything else (in-flight
                # and queued) with the same error.
                for req, _slot in pending:
                    self._finish(req, error=e)
                    self._free_slot_blocks(_slot)
                raise

    # ------------------------------------------------------------------

    def metrics(self) -> dict:
        cache = self.prefix_cache
        # Queue depth is cv-guarded state: snapshot it under the cv in
        # its own scope (never nested with the metrics lock).
        with self._cv:
            queued = len(self._pending)
        # One lock-guarded snapshot of every counter the scheduler
        # mutates, so derived ratios (ttft_avg_s, spec_acceptance_rate)
        # are computed from matching sum/count pairs — never from a
        # torn read taken mid-update.
        with self._mlock:
            snap = {
                "decode_steps": self.steps,
                "decode_dispatches": self.dispatches,
                "prefill_dispatches": self.prefill_dispatches,
                "prefill_tokens": self.prefill_tokens,
                "prefill_chunks": self.prefill_chunks,
                "prompt_rejected_too_long": self.prompt_rejected_too_long,
                "max_prompt_len": self.max_prompt_len,
                "prefill_chunk_tokens": self.prefill_chunk_tokens,
                "requests_admitted": self.admitted,
                "ramp_rounds": self.ramp_rounds,
                "tokens_emitted": self.tokens_emitted,
                "ttft_avg_s": (self.ttft_sum / self.ttft_count
                               if self.ttft_count else 0.0),
                "trace_open": self.trace.open_count,
                "in_flight": self._active_count,
                "peak_in_flight": self.peak_in_flight,
                "queued": queued,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_tokens_reused": self.prefix_tokens_reused,
                "prefix_suffix_tokens": self.prefix_suffix_tokens,
                "prefix_inserts": self.prefix_inserts,
                "spec_drafted_tokens": self.spec_drafted_tokens,
                "spec_accepted_tokens": self.spec_accepted_tokens,
                "spec_verify_dispatches": self.spec_verify_dispatches,
                "spec_draft_dispatches": (self._spec.dispatches
                                          if self._spec is not None else 0),
                "spec_acceptance_rate": (
                    self.spec_accepted_tokens / self.spec_drafted_tokens
                    if self.spec_drafted_tokens else 0.0),
                "spec_draft_k": (sum(self._slot_k) / len(self._slot_k)
                                 if self._slot_k else 0.0),
                "kv_cow_copies": self.kv_cow_copies,
                "kv_shared_blocks": self.kv_shared_blocks,
                "kv_defer_admissions": self.kv_defer_admissions,
                "kv_handoff_exports": self.kv_handoff_exports,
                "kv_handoff_imports": self.kv_handoff_imports,
                "kv_handoff_tokens": self.kv_handoff_tokens,
                "kv_suspends": self.kv_suspends,
                "kv_resumes": self.kv_resumes,
                "kv_host_hits": self.kv_host_hits,
                "kv_peer_hits": self.kv_peer_hits,
                "kv_peer_misses": self.kv_peer_misses,
                "kv_peer_import_bytes": self.kv_peer_import_bytes,
                "kv_peer_fetch_failures": self.kv_peer_fetch_failures,
                "kv_cold_hits": self.kv_cold_hits,
                "kv_cold_demotions": self.kv_cold_demotions,
                "kv_cold_import_bytes": self.kv_cold_import_bytes,
                "kv_import_stale_refused": self.kv_import_stale_refused,
                "kv_import_skipped_crossover":
                    self.kv_import_skipped_crossover,
                "kv_directory_publishes": self.kv_directory_publishes,
                "qos_deadline_shed": self.qos_deadline_shed,
                "hol_bypasses": self.hol_bypasses,
                "qos_enabled": self.qos is not None,
                "tenant_served": dict(self._tenant_served),
                "role": self.role,
                "tp_shards": self.tp_shards,
                "cp_shards": self.cp_shards,
                "pp_stages": self.pp_stages,
                "weight_pushes": self.weight_pushes,
                "weights_stale_refused": self.weight_stale_refused,
                "weight_swap_seconds_last": self.last_swap_seconds,
                "compile_cache_hits": self.compile_cache_hits,
                "compile_cache_misses": self.compile_cache_misses,
                "warm_seconds": self.warm_seconds,
                "warming": self.warming,
            }
        # The weights epoch swaps under the state lock; its own scope
        # (never nested with the other snapshot locks) keeps the read
        # consistent without coupling the lock hierarchies.
        with self._state_lock:
            snap["weights_version"] = self.weights_version
        # Allocator / trie stats live under the prefix lock — taken in a
        # SEPARATE scope (never nested with the metrics lock) so the two
        # subsystems can't deadlock against each other.
        with self._prefix_lock:
            snap["prefix_evictions"] = cache.evictions if cache else 0
            snap["prefix_entries"] = len(cache) if cache else 0
            snap["kv_blocks_total"] = (self._alloc.num_blocks
                                       if self._alloc else 0)
            snap["kv_blocks_in_use"] = (self._alloc.blocks_in_use
                                        if self._alloc else 0)
            snap["kv_blocks_peak"] = self.kv_blocks_peak
            snap["kv_block_size"] = (self.kv_block_size
                                     if self._alloc else 0)
            # Real-byte accounting: the autoscaler must scale on bytes
            # resident, not block counts whose HBM meaning shifts with
            # kv_dtype (an int8 block is ~half an fp block).
            snap["kv_dtype"] = self.kv_dtype if self._alloc else "fp"
            snap["kv_fused"] = self.kv_fused
            snap["kv_bytes_per_token"] = (self._alloc.bytes_per_token
                                          if self._alloc else 0)
            snap["kv_bytes_in_use"] = (self._alloc.bytes_in_use
                                       if self._alloc else 0)
            snap["kv_bytes_total"] = (self._alloc.bytes_total
                                      if self._alloc else 0)
            # Host-tier (HBM -> host) occupancy: the second-chance
            # cache plus pinned suspended-stream payloads. Pinned bytes
            # draining to zero is the suspension leak invariant.
            tier = self._host_tier
            snap["kv_host_tier_bytes"] = tier.bytes_in_use if tier else 0
            snap["kv_host_tier_bytes_total"] = (tier.capacity_bytes
                                                if tier else 0)
            snap["kv_host_tier_pinned_bytes"] = (tier.pinned_bytes
                                                 if tier else 0)
            snap["kv_host_tier_entries"] = len(tier) if tier else 0
            snap["kv_host_demotions"] = tier.demotions if tier else 0
            snap["kv_host_evictions"] = tier.evictions if tier else 0
            snap["kv_host_promotions"] = tier.promotions if tier else 0
            snap["kv_host_tier_high_water_bytes"] = (
                tier.high_water_bytes if tier else 0)
        # Shared-tier stats carry their own leaf locks (the directory
        # and cold store are fleet-shared objects — other replicas'
        # submit probes touch them concurrently with this snapshot).
        if self.cold_store is not None:
            cold = self.cold_store.stats()
            snap["kv_cold_store_bytes"] = cold["bytes_in_use"]
            snap["kv_cold_store_bytes_total"] = cold["capacity_bytes"]
            snap["kv_cold_store_entries"] = cold["entries"]
            snap["kv_cold_store_evictions"] = cold["evictions"]
        if self.kv_directory is not None:
            snap["kv_directory_keys"] = self.kv_directory.stats()["keys"]
        # Histogram-backed latency quantiles (ttft_avg_s above stays for
        # backward compatibility — bench_serving.py and dashboards read
        # it — but the distribution is what autoscaling policies need).
        # Histogram locks are leaves, taken outside the snapshot locks.
        for key, hist in (("ttft", self._h_ttft),
                          ("inter_token", self._h_itl),
                          ("queue_wait", self._h_queue_wait)):
            for q, tag in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                snap[f"{key}_{tag}_s"] = hist.quantile(q)
        return snap
