"""Draft-token proposers for speculative decoding.

The continuous decoder's verify path (models/decode.py:verify_step /
verify_chunk) multiplies decode throughput by scoring K cheap draft
tokens per dispatch — THIS module is where the cheap drafts come from.
Two proposers, both pluggable behind the same ``propose`` surface:

- :class:`NgramProposer` — "prompt lookup" drafting: the continuation
  that followed the most recent earlier occurrence of the context's
  trailing n-gram. Pure host logic, zero device memory, zero model
  cost — the right default for summarization/extraction/code traffic
  where outputs quote their inputs, and for any model that has settled
  into a repeating pattern.
- :class:`DraftModelProposer` — a small registry model
  (``draft_mode="model:<name>"``) holding its OWN decode state over the
  same slot layout as the target. Each round is ONE fused dispatch
  (models/decode.py:extend_and_propose): force-feed the tokens the
  target committed since last round (which silently overwrites anything
  the target rejected — the feed position IS the rollback), then decode
  the next proposals greedily.

Proposals are hints, never promises: verification accepts only what the
target itself would have produced, so a wrong draft costs compute, not
correctness.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.decode import extend_and_propose, init_decode_state
from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.serving.engine import pow2_bucket


class NgramProposer:
    """Host-side prompt/output n-gram lookup.

    ``propose`` scans the context for the most recent earlier occurrence
    of its trailing ``m``-gram (longest match first, ``max_match`` down
    to ``min_match``) and proposes the tokens that followed it. O(len *
    max_match) per call over serving-sized contexts.
    """

    def __init__(self, max_match: int = 3, min_match: int = 1):
        self.max_match = max(1, int(max_match))
        self.min_match = max(1, min(int(min_match), self.max_match))
        self.dispatches = 0  # ngram drafting never touches the device

    def reset(self, slot: int) -> None:  # per-slot state: none
        pass

    def install_weights(self, params) -> None:
        """N-gram drafting has no weights — a live weight push is a
        no-op here (the proposer reads committed tokens, which are
        already the new model's outputs after the swap)."""

    def _lookup(self, context: list[int], n: int) -> list[int]:
        if n <= 0:
            return []
        for m in range(self.max_match, self.min_match - 1, -1):
            if len(context) <= m:
                continue
            pat = context[-m:]
            # Rightmost occurrence strictly before the trailing one —
            # recent repetition predicts the continuation best.
            for i in range(len(context) - m - 1, -1, -1):
                if context[i:i + m] == pat:
                    seg = context[i + m: i + m + n]
                    if seg:
                        return seg
                    break  # the match butts against the tail: shorter m
        return []

    def propose(self, requests: list[tuple[int, list[int], int]],
                ) -> dict[int, list[int]]:
        """``requests``: (slot, context tokens, max proposal length) per
        live row → slot -> proposed tokens (possibly empty)."""
        return {slot: self._lookup(ctx, n) for slot, ctx, n in requests}


class DraftModelProposer:
    """Small draft model sharing the target's slot layout.

    Keeps a private decode state (``slots`` rows, the target's
    ``total_len``) for the draft model and tracks, per slot, how many of
    the request's committed tokens its cache already holds. A propose
    round is one dispatch: catch-up feed + ``propose_steps`` greedy
    tokens per row.
    """

    def __init__(self, model_name: str, target_vocab: int, slots: int,
                 total_len: int, propose_steps: int, seed: int = 0):
        spec = get_model(model_name)
        if spec.family != "transformer":
            raise ValueError(
                f"draft model {model_name!r} is {spec.family}, need a "
                "transformer"
            )
        if spec.config.vocab_size != target_vocab:
            raise ValueError(
                f"draft model {model_name!r} vocab "
                f"{spec.config.vocab_size} != target vocab {target_vocab}"
            )
        self.cfg = spec.config
        self.params = spec.init(jax.random.PRNGKey(seed), self.cfg)
        self.slots = slots
        self.total_len = total_len
        self.propose_steps = max(1, int(propose_steps))
        self.state = init_decode_state(self.cfg, slots, total_len, seed)
        self._fed = [0] * slots  # context tokens already in the draft cache
        self.dispatches = 0

    def reset(self, slot: int) -> None:
        """A new request took ``slot``: its whole prompt is pending feed
        (the stale cache content is overwritten as the feed advances)."""
        self._fed[slot] = 0

    def install_weights(self, params) -> None:
        """Swap in new draft weights (already device-placed by the
        caller). The decoder installs this INSIDE the same state-lock
        epoch as the target's swap — a draft proposing from old weights
        against a new-weights verifier doesn't break correctness
        (verification accepts only what the target would emit) but
        silently collapses acceptance, which is the entire throughput
        win. The draft KV cache is NOT invalidated: positions fed
        before the swap were committed target tokens either way, and
        the proposer's output is a hint the verify pass re-scores."""
        self.params = params

    def propose(self, requests: list[tuple[int, list[int], int]],
                ) -> dict[int, list[int]]:
        if not requests:
            return {}
        pend = {slot: max(len(ctx) - self._fed[slot], 0)
                for slot, ctx, _n in requests}
        width = pow2_bucket(max(max(pend.values()), 1), cap=self.total_len)
        feed = np.zeros((self.slots, width), np.int32)
        # Unused rows park at the cache end: their writes drop on device.
        pos = np.full((self.slots,), self.total_len, np.int32)
        lens = np.zeros((self.slots,), np.int32)
        for slot, ctx, _n in requests:
            p = min(pend[slot], width)
            seg = ctx[self._fed[slot]: self._fed[slot] + p]
            feed[slot, : len(seg)] = seg
            pos[slot] = self._fed[slot]
            lens[slot] = len(seg)
            self._fed[slot] += len(seg)
        self.state, props = extend_and_propose(
            self.state, self.params, self.cfg, jnp.asarray(feed),
            jnp.asarray(pos), jnp.asarray(lens), self.propose_steps)
        self.dispatches += 1
        props = np.asarray(props)
        return {slot: props[slot, :n].tolist() for slot, ctx, n in requests}


def make_proposer(draft_mode: str, *, target_vocab: int, slots: int,
                  total_len: int, propose_steps: int, seed: int = 0):
    """``draft_mode`` → proposer: ``"ngram"`` or ``"model:<registry-name>"``
    (the ``--draft-mode`` flag surface)."""
    if draft_mode == "ngram":
        return NgramProposer()
    if draft_mode.startswith("model:"):
        return DraftModelProposer(
            draft_mode[len("model:"):], target_vocab, slots, total_len,
            propose_steps, seed=seed)
    raise ValueError(
        f"unknown draft_mode {draft_mode!r}; expected 'ngram' or "
        "'model:<registry-name>'"
    )
