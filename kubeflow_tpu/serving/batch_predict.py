"""Batch prediction job: `python -m kubeflow_tpu.serving.batch_predict`.

The tf-batch-predict analogue (kubeflow/tf-batch-predict/
tf-batch-predict.libsonnet): read JSONL instances, run them through the
inference engine in server-batch-size chunks, write JSONL predictions.
Runs as a K8s Job (restartPolicy Never, backoffLimit in the manifest).
"""

from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.runtime import strip_glog_args


def run_batch_predict(engine, input_path: str, output_path: str,
                      batch_size: int, *, log=print) -> dict:
    total = errors = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        chunk: list[dict] = []
        lines: list[int] = []

        def flush():
            nonlocal total, errors
            if not chunk:
                return
            try:
                for inst in chunk:
                    engine.validate_instance(inst)
                preds = engine.predict_batch(chunk)
            except ValueError:
                # Fall back to per-instance so one bad row doesn't kill the
                # whole chunk.
                preds = []
                for inst in chunk:
                    try:
                        engine.validate_instance(inst)
                        preds.extend(engine.predict_batch([inst]))
                    except ValueError as e_one:
                        preds.append({"error": str(e_one)})
                        errors += 1
            for line_no, pred in zip(lines, preds):
                fout.write(json.dumps({"line": line_no, **pred}) + "\n")
            total += len(chunk)
            chunk.clear()
            lines.clear()

        for i, line in enumerate(fin):
            line = line.strip()
            if not line:
                continue
            try:
                chunk.append(json.loads(line))
            except json.JSONDecodeError as e:
                fout.write(json.dumps({"line": i, "error": str(e)}) + "\n")
                errors += 1
                continue
            lines.append(i)
            if len(chunk) >= batch_size:
                flush()
        flush()
    summary = {"instances": total, "errors": errors,
               "output_path": output_path}
    log(f"batch predict done: {json.dumps(summary)}")
    return summary


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="batch prediction job")
    p.add_argument("--model-name", default="lm-test-tiny",
                   help="registry model name")
    p.add_argument("--model-path", default="",
                   help="checkpoint dir (empty = fresh init)")
    p.add_argument("--input-path", required=True, help="JSONL instances")
    p.add_argument("--output-path", required=True, help="JSONL predictions")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--max-seq-len", type=int, default=128)
    args = p.parse_args(argv)

    from kubeflow_tpu.serving.engine import EngineConfig, InferenceEngine

    engine = InferenceEngine(EngineConfig(
        model=args.model_name,
        checkpoint_dir=args.model_path or None,
        batch_size=args.batch_size,
        max_seq_len=args.max_seq_len,
    ))
    run_batch_predict(engine, args.input_path, args.output_path,
                      args.batch_size)
    return 0  # bad rows are recorded in the output, not fatal


if __name__ == "__main__":
    raise SystemExit(main())
