"""Prefix-affine fleet routing primitives: rendezvous hashing + keys.

A replicated decoder pool wants *cache-aware* placement, not round-robin:
`serving/prefix_cache.py` holds each replica's prefix trie on-replica, so
requests sharing a leading-token prefix should concentrate on ONE replica
(its trie warms once and keeps hitting) instead of shattering the prefix
across the fleet. The routing key is therefore a digest of the prompt's
leading tokens, and placement is highest-random-weight (rendezvous)
hashing over the live replica set:

- every (key, replica) pair gets a stable score ``H(replica | key)``;
  the key routes to the top-scoring live replica;
- membership change moves ONLY the keys whose top replica changed —
  ~1/N of keys on scale-up/down, the dead replica's keys on failure —
  while every other key keeps its warm trie (the property consistent
  hashing buys over ``hash(key) % N``).

Digests are BLAKE2 (process- and seed-independent), so the gateway, the
in-process fleet, and a future disaggregated router all place the same
key on the same replica. Pure host logic, no jax — importable by the
gateway without touching the serving stack's device deps.
"""

from __future__ import annotations

import hashlib
from typing import Iterable, Sequence

DEFAULT_AFFINITY_TOKENS = 32


def prefix_affinity_key(tokens: Sequence[int],
                        width: int = DEFAULT_AFFINITY_TOKENS) -> str:
    """Routing key for a prompt: digest of its leading ``width`` token
    ids. Prompts sharing those leading tokens share the key (and so the
    replica, and so the prefix-cache entry); ``width`` should be at
    least the deployment's ``prefix_cache_min_len`` so every cacheable
    prefix maps to one key."""
    head = ",".join(str(int(t)) for t in list(tokens)[: max(int(width), 1)])
    return hashlib.blake2b(head.encode(), digest_size=8).hexdigest()


def _score(key: str, member: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(f"{member}|{key}".encode(), digest_size=8).digest(),
        "big")


def rendezvous_order(key: str, members: Iterable[str]) -> list[str]:
    """Members ordered by descending rendezvous score for ``key`` (ties
    broken by name for determinism). ``order[0]`` is the affine replica;
    the tail is the deterministic spill/failover sequence — excluding a
    dead member never reorders the survivors."""
    return sorted(members, key=lambda m: (-_score(key, m), m))


def rendezvous_pick(key: str, members: Iterable[str]) -> str | None:
    """The affine replica for ``key`` among ``members`` (None if empty)."""
    order = rendezvous_order(key, members)
    return order[0] if order else None
